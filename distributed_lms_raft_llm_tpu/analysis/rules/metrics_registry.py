"""metrics-registry: every emitted metric name is declared exactly once.

A typo'd metric name is the quietest bug in a serving stack: the emitting
code keeps running, the dashboard panel reads zero forever, and the first
time anyone notices is mid-incident. `utils/metrics_registry.py` is now
the single declaration point (name + kind + help string); this rule reads
its declarations as pure AST and proves, project-wide:

- every name handed to `Metrics.inc/set_gauge/hist/time` in the package
  is **declared** — a literal must appear in the registry; a dynamic
  expression must be *rooted at the registry module* (e.g.
  `metric.TUTORING_DEGRADED`, `metric.BREAKER_TRANSITION_COUNTERS[new]`),
  which is declared-by-construction;
- names flow through **one forwarding hop**: a helper whose parameter is
  passed straight into a metrics primitive (`def _inc(self, name):
  self.metrics.inc(name)`) has its *call sites* checked instead, so the
  batcher wrappers don't force suppressions;
- the **registry itself is well-formed**: literal-only declarations (the
  rule must be able to read them without importing), no duplicates, no
  empty help strings;
- every declared series is **emitted somewhere** — a stale declaration
  would put a dead row in the README table the registry renders;
- snapshot/timeline **reads** are checked like emissions: the series
  name handed to the shared readers (`utils/timeline.snap_counter/
  snap_gauge/snap_hist`) and to the Timeline window queries
  (`counter_rate`, `hist_p95`, ...) must be declared too — an SLO bound
  or dashboard row naming a never-declared series would silently read 0
  forever, the read-side twin of the typo'd emission. Reads do NOT count
  as emissions (a series someone only reads is still dead).

Truly dynamic names (the generic `LoopWatchdog`'s `f"{name}_lag"`)
carry a visible `# lint: disable=metrics-registry` with the wiring site
that pins the concrete names.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import Finding, register
from ..project import FunctionInfo, ModuleInfo, Project, ProjectRule

REGISTRY_FILENAME = "metrics_registry.py"
_DECL_FUNCS = {"counter", "gauge", "histogram"}
_EMIT_METHODS = {"inc", "set_gauge", "hist", "time"}
# Receivers that denote a Metrics object: `metrics.inc(...)`,
# `self.metrics.inc(...)`, `self._metrics.inc(...)`.
_METRICS_RECEIVERS = {"metrics", "_metrics"}
# Snapshot/timeline READ sites: function/method name -> positional index
# of the series-name argument (also accepted as keyword `name`). These
# names are the shared reader vocabulary from utils/timeline.py; calls
# to them anywhere in the watched tree are checked like emissions.
_READ_FUNCS: Dict[str, int] = {
    "snap_counter": 1,
    "snap_gauge": 1,
    "snap_hist": 1,
    "counter_rate": 0,
    "counter_delta": 0,
    "hist_rate": 0,
    "hist_p95": 0,
    "gauge_last": 0,
    "gauge_percentile": 0,
}

DEFAULT_WATCH = ("distributed_lms_raft_llm_tpu/",)
DEFAULT_EXCLUDE = (
    # The Metrics implementation itself and the declaration point.
    "distributed_lms_raft_llm_tpu/utils/metrics.py",
    "distributed_lms_raft_llm_tpu/utils/" + REGISTRY_FILENAME,
    # The timeline/scrape mechanism: these DEFINE the generic readers
    # (their internal calls flow parameters, not policy names).
    "distributed_lms_raft_llm_tpu/utils/timeline.py",
    "distributed_lms_raft_llm_tpu/utils/scrape.py",
)


def _is_metrics_call(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in _EMIT_METHODS:
        return False
    recv = func.value
    if isinstance(recv, ast.Name):
        return recv.id in _METRICS_RECEIVERS
    if isinstance(recv, ast.Attribute):
        return recv.attr in _METRICS_RECEIVERS
    return False


def _name_arg(call: ast.Call) -> Optional[ast.expr]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _walk_own(fn_node: ast.AST):
    """Walk a function's body WITHOUT descending into nested def bodies:
    every nested def is its own FunctionInfo and walks itself, so a
    nested forwarder's seam is judged against ITS parameter rather than
    re-walked under the parent (where the parameter looks like a dynamic
    name). Lambdas are not FunctionInfos and stay in the parent walk."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _read_call_arg(call: ast.Call) -> Optional[ast.expr]:
    """The series-name argument of a snapshot/timeline read call, or
    None when `call` is not one of the known readers."""
    func = call.func
    fname = (
        func.attr if isinstance(func, ast.Attribute)
        else func.id if isinstance(func, ast.Name)
        else None
    )
    if fname is None or fname not in _READ_FUNCS:
        return None
    idx = _READ_FUNCS[fname]
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _expr_root(expr: ast.expr) -> Optional[str]:
    """The leftmost Name of an Attribute/Subscript chain, else None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class _Registry:
    def __init__(self) -> None:
        self.rel: Optional[str] = None
        self.names: Dict[str, int] = {}      # metric name -> decl line
        self.problems: List[Tuple[int, str]] = []


def _parse_registry(project: Project, registry_rel: str) -> _Registry:
    reg = _Registry()
    reg.rel = registry_rel
    src = project.sources[registry_rel]
    for node in src.tree.body:
        calls: List[ast.Call] = []
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            calls.append(node.value)
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            calls.append(node.value)
        for call in calls:
            fname = (
                call.func.id if isinstance(call.func, ast.Name)
                else call.func.attr if isinstance(call.func, ast.Attribute)
                else ""
            )
            if fname not in _DECL_FUNCS:
                continue
            args = list(call.args)
            name_node = args[0] if args else None
            help_node = args[1] if len(args) > 1 else None
            for kw in call.keywords:
                if kw.arg == "name":
                    name_node = kw.value
                if kw.arg == "help":
                    help_node = kw.value
            if not (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)):
                reg.problems.append((
                    call.lineno,
                    f"{fname}() declaration must use a literal metric name "
                    "(the lint rule reads this file without importing it)",
                ))
                continue
            name = name_node.value
            if name in reg.names:
                reg.problems.append((
                    call.lineno,
                    f"metric {name!r} declared twice (first at line "
                    f"{reg.names[name]})",
                ))
                continue
            if not (isinstance(help_node, ast.Constant)
                    and isinstance(help_node.value, str)
                    and help_node.value.strip()):
                reg.problems.append((
                    call.lineno,
                    f"metric {name!r} needs a non-empty literal help string",
                ))
                continue
            reg.names[name] = call.lineno
    return reg


@register
class MetricsRegistryRule(ProjectRule):
    name = "metrics-registry"
    description = (
        "metric name emitted somewhere in the package that is not declared "
        "in utils/metrics_registry.py (typo / undocumented series), or a "
        "declared series no code emits"
    )
    # "never declared / never emitted" claims need the whole tree.
    full_project_only = True

    def __init__(
        self,
        watch_prefixes: Sequence[str] = DEFAULT_WATCH,
        exclude_rels: Sequence[str] = DEFAULT_EXCLUDE,
    ):
        self.watch_prefixes = tuple(watch_prefixes)
        self.exclude_rels = tuple(exclude_rels)

    # ------------------------------------------------------------ helpers

    def _registry_rel(self, project: Project) -> Optional[str]:
        for rel in sorted(project.sources):
            # This rule module shares the basename; the declaration point
            # lives outside analysis/.
            if rel.rsplit("/", 1)[-1] == REGISTRY_FILENAME \
                    and "analysis" not in rel.split("/") \
                    and any(rel.startswith(p) for p in self.watch_prefixes):
                return rel
        return None

    def _registry_rooted(
        self, mod: ModuleInfo, expr: ast.expr, registry_rel: str
    ) -> bool:
        root = _expr_root(expr)
        if root is None:
            return False
        target = mod.imports.get(root)
        if target is None:
            return False
        if target[0] == "mod" and target[1] == registry_rel:
            return True
        # `from ..utils.metrics_registry import TUTORING_DEGRADED`
        return target[0] == "sym" and target[1] == registry_rel

    def _find_forwarders(self, project: Project) -> Dict[str, Tuple[str,
                                                                    bool]]:
        """qname -> (forwarded param name, is_read), for helpers that
        pass their first non-self parameter straight into a metrics
        primitive (emission seam) or into one of the snapshot/timeline
        readers (read seam) — call sites are checked instead of the
        seam, and read-forwarded names never count as emissions."""
        forwarders: Dict[str, Tuple[str, bool]] = {}
        for qname, fn in project.functions.items():
            args = fn.node.args.args
            params = [a.arg for a in args if a.arg != "self"]
            if not params:
                continue
            first = params[0]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if _is_metrics_call(node):
                    arg = _name_arg(node)
                    if isinstance(arg, ast.Name) and arg.id == first:
                        forwarders[qname] = (first, False)
                        break
                else:
                    arg = _read_call_arg(node)
                    if isinstance(arg, ast.Name) and arg.id == first:
                        forwarders[qname] = (first, True)
                        break
        return forwarders

    # -------------------------------------------------------------- check

    def check_project(self, project: Project) -> List[Finding]:
        registry_rel = self._registry_rel(project)
        if registry_rel is None:
            return []  # no registry in this project (partial/fixture tree)
        registry = _parse_registry(project, registry_rel)
        reg_src = project.sources[registry_rel]
        findings: List[Finding] = [
            self.finding(reg_src, line, msg)
            for line, msg in registry.problems
        ]
        forwarders = self._find_forwarders(project)
        emitted: Set[str] = set()
        seen: Set[Tuple[str, int]] = set()

        for fn in project.functions.values():
            if not any(fn.rel.startswith(p) for p in self.watch_prefixes):
                continue
            if fn.rel in self.exclude_rels or fn.rel == registry_rel:
                continue
            mod = project.modules[fn.rel]
            own_forward = forwarders.get(fn.qname)
            own_forward_param = own_forward[0] if own_forward else None
            for node in _walk_own(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                is_read = False
                if _is_metrics_call(node):
                    arg = _name_arg(node)
                else:
                    arg = _read_call_arg(node)
                    if arg is not None:
                        is_read = True
                    else:
                        callee = project.resolve_call(
                            mod, node.func, fn.class_name, fn
                        )
                        if callee is None or callee.qname not in forwarders:
                            continue
                        is_read = forwarders[callee.qname][1]
                        arg = node.args[0] if node.args else None
                if arg is None:
                    continue
                # Defensive dedup (a call reachable from two walks):
                # col_offset keeps two emissions sharing a source line
                # distinct.
                key = (fn.rel, node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                # `"a" if cond else "b"` names two series; check both.
                branches = (
                    [arg.body, arg.orelse] if isinstance(arg, ast.IfExp)
                    else [arg]
                )
                if all(isinstance(b, ast.Constant)
                       and isinstance(b.value, str) for b in branches):
                    for b in branches:
                        if not is_read:
                            emitted.add(b.value)
                        if b.value not in registry.names:
                            what = ("read" if is_read else "emission")
                            why = (
                                "an SLO bound or dashboard row on it "
                                "reads 0 forever" if is_read else
                                "a typo here ships an always-zero "
                                "dashboard panel"
                            )
                            findings.append(self.finding(
                                fn.src, node,
                                f"metric name {b.value!r} at this {what} "
                                f"site is not declared in {registry_rel} "
                                f"— {why}; declare it with a help string "
                                "(or fix the spelling)",
                            ))
                    continue
                if isinstance(arg, ast.Name) and arg.id == own_forward_param:
                    continue  # the forwarding seam; call sites are checked
                if self._registry_rooted(mod, arg, registry_rel):
                    continue  # registry constants are declared by construction
                findings.append(self.finding(
                    fn.src, node,
                    "metric name is not statically checkable (dynamic "
                    "expression); use a string literal or a constant/"
                    "mapping from the metrics registry so the series "
                    "stays declared",
                ))

        # Declared-but-never-emitted: a dead registry row becomes a dead
        # row in the rendered docs. A name counts as emitted when it
        # appears literally at an emission site, or when some watched
        # module references the registry constant (or constant-valued
        # mapping) that carries it.
        referenced = self._constant_referenced_names(
            project, registry_rel, registry.names
        )
        for name, line in sorted(registry.names.items()):
            if name not in emitted and name not in referenced:
                findings.append(self.finding(
                    reg_src, line,
                    f"metric {name!r} is declared but nothing emits it — "
                    "delete the declaration or wire the emission",
                ))
        return findings

    def _constant_referenced_names(
        self, project: Project, registry_rel: str, names: Dict[str, int]
    ) -> Set[str]:
        """Names bound to module-level registry constants (or grouped in
        module-level dict literals) that some watched module references."""
        src = project.sources[registry_rel]
        const_to_name: Dict[str, Set[str]] = {}
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target = node.target
            else:
                continue
            if not isinstance(target, ast.Name):
                continue
            bound: Set[str] = set()
            if isinstance(node.value, ast.Call):
                call = node.value
                if call.args and isinstance(call.args[0], ast.Constant) \
                        and isinstance(call.args[0].value, str):
                    bound.add(call.args[0].value)
            elif isinstance(node.value, ast.Dict):
                for v in node.value.values:
                    if isinstance(v, ast.Name) and v.id in const_to_name:
                        bound |= const_to_name[v.id]
                    elif isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        bound.add(v.value)
            if bound:
                const_to_name[target.id] = bound & set(names)
        referenced: Set[str] = set()
        for rel, mod in project.modules.items():
            if rel == registry_rel or not any(
                rel.startswith(p) for p in self.watch_prefixes
            ):
                continue
            for node in ast.walk(mod.src.tree):
                const = None
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name):
                    target = mod.imports.get(node.value.id)
                    if target is not None and target[0] == "mod" \
                            and target[1] == registry_rel:
                        const = node.attr
                elif isinstance(node, ast.Name):
                    target = mod.imports.get(node.id)
                    if target is not None and target[0] == "sym" \
                            and target[1] == registry_rel:
                        const = target[2]
                if const is not None and const in const_to_name:
                    referenced |= const_to_name[const]
        return referenced
