"""donation-safety: a donated buffer is dead — nothing may read it after
dispatch.

Every engine state program donates its input (`donate_argnums` on
`_step`/`_install`/`_grow`/`_decode`): XLA reuses the buffers in place,
which is the entire reason admission and decode don't copy the KV cache
every step. The contract is invisible at the call site, and breaking it
is a runtime crash ("array has been deleted") that only fires on backends
that actually alias — or worse, a silent read of reused memory. The
engine's own `reset()` docstring documents the failure mode; this rule
makes the contract structural.

Findings (analysis/absint.py supplies the jit-site scan and the
branch-aware statement ordering):

- **read-after-donate**: an argument at a donated position of a known
  donating callable is read later in the same function — on a path that
  executes after the dispatch — without an intervening rebinding.
- **alias-read**: the donated binding was aliased (`snap = state`) before
  the dispatch and the alias is read after it; two live names for one
  donated buffer is the same bug wearing a disguise.
- **loop-no-rebind**: the dispatch sits in a loop and nothing in the loop
  body rebinds the donated name — iteration 2 feeds the program a deleted
  buffer.
- **unbound-attr-donate**: a donated `self.<attr>` whose result does not
  rebind `self.<attr>` in the same statement. The attribute outlives the
  function, so the NEXT entry into any method reads deleted buffers; the
  live engine always writes `self.state = self._step(..., self.state,
  ...)` in one statement.

Reads the analysis cannot attribute (dynamic dispatch, cross-function
attribute flows) contribute nothing — the standard unsound-by-design
trade (analysis/project.py docstring).
"""

from __future__ import annotations

import ast
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from .. import absint
from ..core import Finding, register
from ..project import FunctionInfo, Project, ProjectRule


def _call_key(
    call: ast.Call, fn: FunctionInfo
) -> Optional[Tuple[str, str, str]]:
    """Donor-lookup key for a call expression: ("attr", class, name) for
    `self.name(...)`, ("name", rel, name) for bare `name(...)`."""
    func = call.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
        and fn.class_name is not None
    ):
        return ("attr", fn.class_name, func.attr)
    if isinstance(func, ast.Name):
        return ("name", fn.rel, func.id)
    return None


def _result_targets(call: ast.Call) -> Set[str]:
    """Chains the statement containing `call` assigns the call's result to
    (through subscripts like `self._step(...)[0]` and tuple unpacking)."""
    node: ast.AST = call
    parent = getattr(node, "parent", None)
    while isinstance(parent, (ast.Subscript, ast.Starred)):
        node, parent = parent, getattr(parent, "parent", None)
    if isinstance(parent, ast.Assign):
        return absint.assigned_chains(parent)
    if isinstance(parent, (ast.AugAssign, ast.AnnAssign)):
        return absint.assigned_chains(parent)
    return set()


def _enclosing_loop(
    src_parents: Iterable[ast.AST], fn_node: ast.AST
) -> Optional[ast.AST]:
    for anc in src_parents:
        if anc is fn_node:
            return None
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return anc
    return None


def _within(node: ast.AST, container: ast.AST) -> bool:
    cur: Optional[ast.AST] = node
    while cur is not None:
        if cur is container:
            return True
        cur = getattr(cur, "parent", None)
    return False


@register
class DonationSafetyRule(ProjectRule):
    name = "donation-safety"
    description = (
        "a buffer passed at a donated position of a jitted program is read "
        "(directly, via an alias, or on a later loop iteration) after the "
        "dispatch, or a donated engine attribute is not rebound by its own "
        "statement — donated buffers are deleted/reused by XLA and every "
        "later read is a crash or garbage"
    )

    def __init__(
        self, watch_prefixes: Sequence[str] = (absint.ENGINE_PREFIX,)
    ):
        self.watch_prefixes = tuple(watch_prefixes)

    def check_project(self, project: Project) -> List[Finding]:
        donors: Dict[Tuple[str, str, str], Tuple[int, ...]] = {}
        for site in absint.scan_jit_sites(project, self.watch_prefixes):
            if not site.donate_argnums or not site.attr:
                continue
            if site.is_self_attr:
                donors[("attr", site.owner, site.attr)] = site.donate_argnums
            else:
                donors[("name", site.rel, site.attr)] = site.donate_argnums
        if not donors:
            return []
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        def report(fn: FunctionInfo, node: ast.AST, msg: str) -> None:
            key = (fn.rel, getattr(node, "lineno", 0), msg)
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    rule=self.name, path=fn.rel,
                    line=getattr(node, "lineno", 0), message=msg,
                ))

        for fn in project.functions_in(self.watch_prefixes):
            self._check_function(fn, donors, report)
        return findings

    # ------------------------------------------------------------------

    def _check_function(
        self,
        fn: FunctionInfo,
        donors: Dict[Tuple[str, str, str], Tuple[int, ...]],
        report: Callable[[FunctionInfo, ast.AST, str], None],
    ) -> None:
        fn_node = fn.node
        calls: List[Tuple[ast.Call, Tuple[int, ...]]] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Call):
                key = _call_key(node, fn)
                if key is not None and key in donors:
                    calls.append((node, donors[key]))
        if not calls:
            return
        # All loads/assignments in the function, with their order chains.
        loads: List[Tuple[str, ast.AST, List]] = []
        assigns: List[Tuple[Set[str], ast.AST, List]] = []
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                chain = absint.chain_str(node)
                if chain is not None:
                    loads.append(
                        (chain, node, absint.stmt_chain(node, fn_node))
                    )
            chains = absint.assigned_chains(node)
            if chains:
                assigns.append(
                    (chains, node, absint.stmt_chain(node, fn_node))
                )

        for call, positions in calls:
            call_chain = absint.stmt_chain(call, fn_node)
            rebinds = _result_targets(call)
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                donated = absint.chain_str(arg)
                if donated is None:
                    continue
                self._check_one_donation(
                    fn, fn_node, call, call_chain, rebinds, donated,
                    loads, assigns, report,
                )

    def _check_one_donation(
        self,
        fn: FunctionInfo,
        fn_node: ast.AST,
        call: ast.Call,
        call_chain: List[Tuple[int, str, int]],
        rebinds: Set[str],
        donated: str,
        loads: List[Tuple[str, ast.AST, List[Tuple[int, str, int]]]],
        assigns: List[Tuple[Set[str], ast.AST, List[Tuple[int, str, int]]]],
        report: Callable[[FunctionInfo, ast.AST, str], None],
    ) -> None:
        rebound_here = donated in rebinds

        # unbound-attr-donate: self.<attr> escapes the function scope.
        if donated.startswith("self.") and not rebound_here:
            report(fn, call, (
                f"donated attribute `{donated}` is not rebound by this "
                "statement — the attribute outlives the call and the next "
                "dispatch reads deleted buffers; write "
                f"`{donated} = <program>(...)` in one statement (see "
                "PagedEngine.reset's failure note)"
            ))
            return

        # loop-no-rebind: iteration 2 re-reads the donated name.
        loop = _enclosing_loop(
            fn.src.parents(call) if hasattr(fn, "src") else [], fn_node
        )
        if loop is not None and not rebound_here:
            rebound_in_loop = any(
                donated in chains and _within(node, loop)
                for chains, node, _ in assigns
            )
            if not rebound_in_loop:
                report(fn, call, (
                    f"`{donated}` is donated inside a loop and never "
                    "rebound in the loop body — the next iteration "
                    "dispatches a deleted buffer"
                ))
                return

        # read-after-donate (+ alias-read): any Load of the donated chain
        # (or an alias of it) ordered after the call, with no rebinding
        # ordered between. When the dispatch statement itself rebinds the
        # donated name, later reads of THAT name see the program's result
        # (fine) — but a pre-existing alias still points at the donated
        # buffer, so aliases stay checked.
        aliases = {donated}
        for chains, node, chain in assigns:
            if isinstance(node, ast.Assign) and absint.chain_str(
                node.value
            ) == donated:
                before = absint.execution_order(chain, call_chain)
                if before:
                    aliases.update(chains)
        if rebound_here:
            aliases.discard(donated)
            if not aliases:
                return
        for name, node, chain in loads:
            hit = any(
                name == a or name.startswith(a + ".") for a in aliases
            )
            if not hit or _within(node, call):
                continue
            after = absint.execution_order(call_chain, chain)
            if not after:
                continue
            killed = False
            for chains, anode, achain in assigns:
                if not any(
                    a in chains for a in aliases
                    if name == a or name.startswith(a + ".")
                ):
                    continue
                if absint.execution_order(call_chain, achain) and (
                    absint.execution_order(achain, chain) is not False
                ):
                    killed = True
                    break
            if killed:
                continue
            direct = name == donated or name.startswith(donated + ".")
            which = "" if direct else f" (alias of `{donated}`)"
            report(fn, node, (
                f"`{name}`{which} is read after being donated to a jitted "
                f"program at line {call.lineno} — the buffer is deleted or "
                "reused by then; read results from the program's RETURN "
                "value, or drop the donation"
            ))
