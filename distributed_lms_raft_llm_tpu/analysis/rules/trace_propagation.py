"""trace-propagation: handler-reachable stub egress must forward the
request's trace context.

The flight recorder (utils/tracing.py) reconstructs one request's journey
across processes by riding an `x-trace-context` metadata header on every
gRPC hop. That chain is only as strong as its weakest egress: ONE stub
call built with bare metadata (or none) and every span downstream of it
re-roots as an orphan fragment — the waterfall silently loses the engine
spans, which is precisely the part of the 1.69 s p50 every perf PR needs
to see. Silent, because nothing errors: traces just come back shallower.

This rule makes the chain structural, the same way deadline-flow made
budget propagation structural: **every awaited gRPC stub egress reachable
from an RPC handler in the request-path modules (`lms/`, `serving/`) must
build its metadata through `trace_metadata(...)`** — the one sanctioned
wrapper, which appends the current span's context to whatever base
metadata the call already carries.

Mechanics (analysis/project.py, shared with deadline-flow):

- roots are the async methods of `*Servicer` subclasses plus every
  address-taken function (the post-commit replication sweep is reached
  through `apply_cb=self._apply`);
- reachability is the call-graph closure over those roots;
- a "stub egress" is an **awaited** method call whose attribute is
  CamelCase — the proto naming convention separating wire RPCs
  (`FetchFile`, `GetLLMAnswer`) from snake_case helpers; the await
  requirement keeps protobuf constructors (`lms_pb2.FetchFileRequest`,
  also CamelCase, never awaited) out of scope. A second shape is also
  matched: a CamelCase call carrying a `timeout=` keyword whose handle
  is awaited *later* (the fleet router holds the call object to read
  the `x-served-by` response trailer) — constructors never pass
  `timeout=`, so they stay out of scope. A third shape covers
  server-streaming egress: a CamelCase call consumed as an **async-for
  iterable** (`async for chunk in stub.StreamLLMAnswer(...)`) — the
  iteration context rules out constructors even without a `timeout=`
  keyword, so a metadata-dropping stream forward cannot hide from the
  rule by dropping the timeout too;
- the async functions of the router/pool egress modules
  (`DEFAULT_EGRESS_ROOTS`, e.g. `lms/tutoring_pool.py`) are roots in
  their own right: they run per-request behind `self.pool.forward(...)`
  attribute calls the call graph cannot resolve;
- the finding fires when the call has no `metadata=` keyword, or one
  whose value is not a direct `trace_metadata(...)` call. Wrapping the
  existing expression (`metadata=trace_metadata(deadline.to_metadata())`)
  is the fix shape and never flags.

Raft-internal RPCs (`raft/grpc_transport.py`) are deliberately out of
scope: heartbeats and appends are protocol traffic, not request traffic —
tracing them would churn the ring and say nothing a request-scoped
`raft.commit` span doesn't (see the tracing module docstring).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from ..core import Finding, register
from ..project import (
    EGRESS_ROOT_MODULES,
    Project,
    ProjectRule,
)

# Request-path modules: where request-scoped trace context lives.
DEFAULT_WATCH = (
    "distributed_lms_raft_llm_tpu/lms/",
    "distributed_lms_raft_llm_tpu/serving/",
)

# Router/pool egress modules: their async functions are per-request
# egress invoked through instance attributes (`self.pool.forward`),
# which the call graph cannot resolve — treat them as roots so the fleet
# router's own stub egress is held to the same contract (see
# deadline_flow.DEFAULT_EGRESS_ROOTS).
DEFAULT_EGRESS_ROOTS = EGRESS_ROOT_MODULES

# The sanctioned metadata-building wrapper (utils/tracing.py).
WRAPPER = "trace_metadata"


def _awaited_stub_egress(node: ast.Await) -> Optional[ast.Call]:
    """The awaited Call when `node` awaits a CamelCase-method stub RPC."""
    call = node.value
    if not isinstance(call, ast.Call):
        return None
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr[:1].isupper():
        return call
    return None


def _metadata_kw(call: ast.Call) -> Optional[ast.keyword]:
    for kw in call.keywords:
        if kw.arg == "metadata":
            return kw
    return None


def _is_wrapper_call(expr: ast.expr) -> bool:
    """`trace_metadata(...)` (bare or module-qualified)."""
    if not isinstance(expr, ast.Call):
        return False
    func = expr.func
    if isinstance(func, ast.Name):
        return func.id == WRAPPER
    if isinstance(func, ast.Attribute):
        return func.attr == WRAPPER
    return False


@register
class TracePropagationRule(ProjectRule):
    name = "trace-propagation"
    description = (
        "gRPC stub egress reachable from an RPC handler whose metadata is "
        "not built via utils.tracing.trace_metadata(...) — the request's "
        "x-trace-context is dropped and every downstream span re-roots as "
        "an orphan fragment; wrap the existing metadata expression"
    )

    def __init__(self, watch_prefixes: Sequence[str] = DEFAULT_WATCH,
                 egress_roots: Sequence[str] = DEFAULT_EGRESS_ROOTS):
        self.watch_prefixes = tuple(watch_prefixes)
        self.egress_roots = tuple(egress_roots)

    def check_project(self, project: Project) -> List[Finding]:
        roots = project.handler_roots() | project.address_taken
        roots |= {
            fn.qname for fn in project.functions_in(self.egress_roots)
            if fn.is_async
        }
        reachable = project.reachable(roots)
        findings: List[Finding] = []
        seen = set()
        for fn in project.functions_in(self.watch_prefixes):
            if fn.qname not in reachable:
                continue
            for node in ast.walk(fn.node):
                # Two egress shapes: `await stub.Rpc(...)` (the common
                # case), and a stub call whose handle is awaited later
                # so the caller can read trailing metadata — recognized
                # by its `timeout=` keyword, which protobuf constructors
                # (the other CamelCase calls) never carry.
                call = None
                if isinstance(node, ast.Await):
                    call = _awaited_stub_egress(node)
                elif isinstance(node, ast.AsyncFor) \
                        and isinstance(node.iter, ast.Call):
                    # Server-streaming egress: the stream call is never
                    # awaited directly — its chunks arrive through the
                    # async-for — but every chunk still rides the hop
                    # this call's metadata opened.
                    func = node.iter.func
                    if isinstance(func, ast.Attribute) \
                            and func.attr[:1].isupper():
                        call = node.iter
                elif isinstance(node, ast.Call):
                    func = node.func
                    if (isinstance(func, ast.Attribute)
                            and func.attr[:1].isupper()
                            and any(kw.arg == "timeout"
                                    for kw in node.keywords)):
                        call = node
                if call is None:
                    continue
                rpc = call.func.attr  # type: ignore[union-attr]
                kw = _metadata_kw(call)
                if kw is not None and _is_wrapper_call(kw.value):
                    continue
                # col_offset keeps two egresses sharing a line distinct;
                # the dedup collapses only the nested-def re-walk.
                key = (fn.rel, call.lineno, call.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                what = (
                    "carries metadata that bypasses trace_metadata()"
                    if kw is not None else "sends no metadata at all"
                )
                findings.append(self.finding(
                    fn.src, call,
                    f"{rpc}(...) is reachable from an RPC handler but "
                    f"{what} — the x-trace-context chain breaks here and "
                    "every downstream span re-roots as an orphan "
                    "fragment; build the metadata with utils.tracing."
                    "trace_metadata(<existing metadata or None>)",
                ))
        return findings
