"""guarded-by: annotated shared state only mutates under its lock.

The convention (documented in the README):

    class Metrics:
        def __init__(self):
            self._counters = {}          # guarded-by: _lock
            self._lock = threading.Lock()

Every mutation of `self._counters` anywhere in the class — assignment,
augmented assignment, subscript store, `del`, or a mutating method call
(`.append`, `.pop`, `.clear`, ...) — must then occur lexically inside
`with self._lock:` (checked), inside a method whose `def` line carries the
same `# guarded-by: _lock` annotation (meaning "callers hold the lock" —
and calls to such methods are themselves checked to be under the lock), or
inside `__init__` (construction happens-before sharing).

Two special guard names cover the repo's lock-free confinement patterns:

- `# guarded-by: event-loop` — asyncio-confined state (the batcher
  queues). Checked property: the attribute is never mutated from inside a
  function/lambda handed to `run_in_executor`, `executor.submit`, or
  `threading.Thread` — the exact escape that would turn loop confinement
  into a data race.
- A guard name that names another attribute is assumed to be a
  `threading.Lock`-like object used via `with self.<name>`.

The check is lexical by design: it cannot prove the absence of races, but
it turns "who guards this?" from tribal knowledge into a machine-checked
annotation, which is what caught nothing before PR 1's review and would
have caught it after.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import Finding, Rule, Source, register

_ANNOT_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w\-]*)")

EVENT_LOOP = "event-loop"

# Method names that mutate their receiver in place.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "put_nowait",
}

_EXECUTOR_FUNCS = {"run_in_executor", "submit", "Thread", "Timer"}


def _line_annotation(src: Source, lineno: int) -> Optional[str]:
    """Annotation on the statement's line, or on a pure-comment line
    directly above it (for declarations too long for a trailing comment)."""
    if 1 <= lineno <= len(src.lines):
        m = _ANNOT_RE.search(src.lines[lineno - 1])
        if m:
            return m.group(1)
    if lineno >= 2:
        above = src.lines[lineno - 2].strip()
        if above.startswith("#"):
            m = _ANNOT_RE.search(above)
            if m:
                return m.group(1)
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guards: Dict[str, str] = {}         # attr -> guard name
        self.locked_methods: Dict[str, str] = {}  # method -> guard name


def _collect(src: Source, cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(cls)
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            guard = _line_annotation(src, node.lineno)
            if guard is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    info.guards[attr] = guard
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            guard = _line_annotation(src, node.lineno)
            if guard is not None:
                info.locked_methods[node.name] = guard
    return info


def _enclosing_method(src: Source, node: ast.AST,
                      cls: ast.ClassDef) -> Optional[ast.AST]:
    fn = None
    for anc in src.parents(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = fn or anc
        if anc is cls:
            return fn
    return fn


def _under_lock(src: Source, node: ast.AST, lock: str,
                info: _ClassInfo) -> bool:
    for anc in src.parents(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                if _self_attr(expr) == lock:
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Inside a method annotated "callers hold this lock".
            if info.locked_methods.get(anc.name) == lock:
                return True
            if anc.name == "__init__":
                return True  # construction happens-before sharing
            break  # left the method body; a lock further out doesn't count
    return False


def _escapes_to_thread(src: Source, node: ast.AST) -> bool:
    """True when `node` sits in a def/lambda that is passed to an executor
    or thread constructor (the loop-confinement escape hatch)."""
    for anc in src.parents(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # A lambda is passed directly (its parent is the executor
            # call); a def is referenced by name — look for the name as an
            # argument to an executor call in the enclosing function.
            parent = getattr(anc, "parent", None)
            if isinstance(parent, ast.Call) and _is_executor_call(parent):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                outer = _outer_function(src, anc)
                if outer is not None and _name_passed_to_executor(
                    outer, anc.name
                ):
                    return True
    return False


def _is_executor_call(call: ast.Call) -> bool:
    func = call.func
    name = (
        func.attr if isinstance(func, ast.Attribute)
        else func.id if isinstance(func, ast.Name) else ""
    )
    return name in _EXECUTOR_FUNCS


def _outer_function(src: Source, fn: ast.AST) -> Optional[ast.AST]:
    for anc in src.parents(fn):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _name_passed_to_executor(scope: ast.AST, name: str) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and _is_executor_call(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
    return False


@register
class GuardedByRule(Rule):
    name = "guarded-by"
    description = (
        "mutation of a `# guarded-by:` annotated attribute outside its "
        "lock (`with self._lock:`), or an event-loop-confined attribute "
        "mutated from executor/thread context"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(src.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(src, cls))
        return findings

    def _check_class(self, src: Source, cls: ast.ClassDef) -> List[Finding]:
        info = _collect(src, cls)
        if not info.guards and not info.locked_methods:
            return []
        findings: List[Finding] = []
        for node in ast.walk(cls):
            for attr, mutation in self._mutations(node):
                guard = info.guards.get(attr)
                if guard is None:
                    continue
                if guard == EVENT_LOOP:
                    if _escapes_to_thread(src, node):
                        findings.append(self.finding(
                            src, node,
                            f"self.{attr} is event-loop-confined "
                            f"(guarded-by: {EVENT_LOOP}) but this {mutation} "
                            "runs in executor/thread context — that is a "
                            "data race with the loop",
                        ))
                elif not _under_lock(src, node, guard, info):
                    findings.append(self.finding(
                        src, node,
                        f"{mutation} of self.{attr} outside `with "
                        f"self.{guard}:` (declared guarded-by: {guard}); "
                        "take the lock or annotate the enclosing method "
                        f"`# guarded-by: {guard}` if callers hold it",
                    ))
            # Calls to lock-annotated methods must themselves hold the lock.
            if isinstance(node, ast.Call):
                method_attr = _self_attr(node.func)
                if method_attr is not None:
                    lock = info.locked_methods.get(method_attr)
                    if lock is not None and lock != EVENT_LOOP and not \
                            _under_lock(src, node, lock, info):
                        findings.append(self.finding(
                            src, node,
                            f"self.{method_attr}() requires `{lock}` held "
                            f"(its def is annotated guarded-by: {lock}) but "
                            "this call site does not hold it",
                        ))
        return findings

    @staticmethod
    def _mutations(node: ast.AST) -> "Iterator[Tuple[str, str]]":
        """Yield (attr, description) for mutations of self.<attr>."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _self_attr(t)
                if attr is not None and not isinstance(node, ast.Assign):
                    yield attr, "augmented assignment"
                elif attr is not None:
                    # Plain rebinding in __init__ is the declaration; the
                    # under-lock check exempts __init__ anyway.
                    yield attr, "assignment"
                # self._x[k] = v / self._x[k] += v
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, "subscript store"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, "del"
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, "subscript del"
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                attr = _self_attr(func.value)
                if attr is not None:
                    yield attr, f".{func.attr}() call"
