"""deadline-flow: RPC egress reachable from a handler must spend budget,
not wall-clock constants.

PR 1 threaded one request-scoped `Deadline` through the student-query
path, but two gRPC egresses kept hardcoded timeouts (`timeout=5` on the
blob FetchFile sweep, `timeout=30` per peer on upload replication): a
client whose budget had already expired could still pin this server for
tens of seconds doing work nobody would receive. This rule makes the
contract structural: **every gRPC stub call reachable from an RPC
handler in the request-path modules (`lms/`, `serving/`) must derive its
`timeout=` from the propagated budget** — a numeric literal there is a
finding.

Mechanics (analysis/project.py):

- roots are the async methods of `*Servicer` subclasses plus every
  address-taken function (callbacks like `apply_cb=self._apply` run on
  the same loop in response to the same RPCs, which is exactly how the
  post-commit replication sweep is reached);
- reachability is the call-graph closure over those roots;
- a "gRPC stub egress" is a method call whose attribute is CamelCase —
  the proto naming convention (`FetchFile`, `SendFile`, `GetLLMAnswer`)
  that separates wire RPCs from snake_case helpers like
  `asyncio.wait_for` in this codebase;
- the finding fires on `timeout=<int|float literal>` at such a call. A
  timeout *expression* (`deadline.timeout(cap=...)`, `max(floor, ...)`)
  is the fix shape and never flags, so the rule cannot pester correct
  code into suppressions;
- server-streaming egress is held to the same contract through a second
  shape: a CamelCase call consumed as an **async-for iterable**
  (`async for chunk in stub.StreamLLMAnswer(...)`). A stream with NO
  `timeout=` at all is a finding there — an open stream outlives any
  client budget silently, and the async-for context rules out protobuf
  constructors, so the missing-keyword check that would be too noisy on
  plain calls is sound on this shape. Literal timeouts on streaming
  calls are caught by the ordinary literal check above;
- the async functions of the router/pool egress modules
  (`DEFAULT_EGRESS_ROOTS`, e.g. `lms/tutoring_pool.py`) are roots in
  their own right: they run per-request behind `self.pool.forward(...)`
  attribute calls the call graph cannot resolve, and they hold the
  hottest timeout in the system (the hedged tutoring forward).

Raft-internal RPC timing (`raft/grpc_transport.py`) is deliberately out
of scope: heartbeat-scale protocol timeouts are a consensus-liveness
knob, not a client budget.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Tuple

from ..core import Finding, register
from ..project import (
    EGRESS_ROOT_MODULES,
    Project,
    ProjectRule,
)

# Request-path modules: where client deadline budgets live.
DEFAULT_WATCH = (
    "distributed_lms_raft_llm_tpu/lms/",
    "distributed_lms_raft_llm_tpu/serving/",
)

# Router/pool egress modules: their async functions run per-request but
# are invoked through instance attributes (`self.pool.forward(...)`),
# which the call graph's heuristics cannot resolve into an edge from the
# Servicer handler — so they are treated as roots in their own right.
# Without this, the fleet router's stub egress (the hottest timeout in
# the system) would silently fall out of the rule's reachable set.
# Shared with trace-propagation (analysis/project.py) so the two rules
# cannot drift.
DEFAULT_EGRESS_ROOTS = EGRESS_ROOT_MODULES


def _literal_timeout(call: ast.Call) -> Tuple[bool, object]:
    for kw in call.keywords:
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, (int, float)) \
                and not isinstance(kw.value.value, bool):
            return True, kw.value.value
    return False, None


def _stub_egress_name(call: ast.Call) -> str:
    """The CamelCase RPC method name, or '' when not a stub egress."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr[:1].isupper():
        return func.attr
    return ""


@register
class DeadlineFlowRule(ProjectRule):
    name = "deadline-flow"
    description = (
        "gRPC stub egress reachable from an RPC handler with a hardcoded "
        "numeric `timeout=` — the client's propagated Deadline budget is "
        "dropped on the floor; derive the timeout from it "
        "(utils/resilience.Deadline.timeout)"
    )

    def __init__(self, watch_prefixes: Sequence[str] = DEFAULT_WATCH,
                 egress_roots: Sequence[str] = DEFAULT_EGRESS_ROOTS):
        self.watch_prefixes = tuple(watch_prefixes)
        self.egress_roots = tuple(egress_roots)

    def check_project(self, project: Project) -> List[Finding]:
        roots = project.handler_roots() | project.address_taken
        roots |= {
            fn.qname for fn in project.functions_in(self.egress_roots)
            if fn.is_async
        }
        reachable = project.reachable(roots)
        findings: List[Finding] = []
        seen = set()
        for fn in project.functions_in(self.watch_prefixes):
            if fn.qname not in reachable:
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.AsyncFor) \
                        and isinstance(node.iter, ast.Call):
                    # Server-streaming egress consumed as an async-for
                    # iterable: a stream opened with NO timeout at all
                    # runs unbounded past any client budget. (A literal
                    # timeout on the same call is caught by the plain
                    # Call branch below.)
                    call = node.iter
                    rpc = _stub_egress_name(call)
                    if rpc and not any(kw.arg == "timeout"
                                       for kw in call.keywords):
                        key = (fn.rel, call.lineno, call.col_offset)
                        if key in seen:
                            continue
                        seen.add(key)
                        findings.append(self.finding(
                            fn.src, call,
                            f"async for ... in {rpc}(...) opens a "
                            "server stream with no timeout — the stream "
                            "outlives the client's propagated Deadline "
                            "budget and can pin this server "
                            "indefinitely; pass timeout=Deadline."
                            "timeout(cap=...) on the stream call",
                        ))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                rpc = _stub_egress_name(node)
                if not rpc:
                    continue
                hardcoded, value = _literal_timeout(node)
                if not hardcoded:
                    continue
                # col_offset keeps two egresses sharing a line distinct;
                # the dedup only collapses the nested-def re-walk.
                key = (fn.rel, node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(self.finding(
                    fn.src, node,
                    f"{rpc}(..., timeout={value}) is reachable from an RPC "
                    "handler but ignores the request's propagated Deadline "
                    "budget — an expired client can still pin this server "
                    f"for {value}s; derive the timeout from the active "
                    "budget (Deadline.timeout(cap=...)) with a configured "
                    "floor/cap in [resilience]",
                ))
        return findings
