"""slow-marker: soak-shaped tests must carry `@pytest.mark.slow`.

The original repo-native rule (previously `scripts/audit_markers.py`, now a
thin shim over this module): tier-1 runs `pytest -m 'not slow'` under a
hard timeout, so ONE unmarked soak blows the whole budget. Any test
function whose name advertises a long-running shape (`soak`, `sustained`,
`stress_many`) must be marked slow — directly, on its class, or via a
module-level `pytestmark`.

Semester-sim coverage: a `SimConfig(duration_s=N)` constructed in a test
file runs a WALL-CLOCK workload of N seconds regardless of what the test
is named, so any construction with a literal `duration_s` beyond
`SIM_TIER1_DURATION_MAX_S` must sit inside a slow-marked function (or a
slow-marked class/module) — the soak belongs to tier-2 whatever it calls
itself.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List

from ..core import Finding, Rule, Source, register

# Name fragments that mean "this test is a soak, not a unit test".
SLOW_NAME_HINTS = ("soak", "sustained", "stress_many")

# A sim workload longer than this is tier-2 by construction: the tier-1
# semester sim budgets ~20-30 s of wall clock INCLUDING boot/settle/audit
# around its (shorter) duration_s.
SIM_TIER1_DURATION_MAX_S = 60.0
_SIM_CONFIG_NAMES = ("SimConfig",)


def _is_slow_mark(node: ast.expr) -> bool:
    """True for `pytest.mark.slow` / `mark.slow` (bare or called)."""
    if isinstance(node, ast.Call):
        node = node.func
    return isinstance(node, ast.Attribute) and node.attr == "slow"


def _module_marked_slow(tree: ast.Module) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            if "pytestmark" in targets:
                values = (
                    stmt.value.elts
                    if isinstance(stmt.value, (ast.List, ast.Tuple))
                    else [stmt.value]
                )
                if any(_is_slow_mark(v) for v in values):
                    return True
    return False


@register
class SlowMarkerRule(Rule):
    name = "slow-marker"
    description = (
        "test whose name advertises a soak shape (soak/sustained/"
        "stress_many) lacks @pytest.mark.slow — it would blow the tier-1 "
        "timeout"
    )

    def applies_to(self, rel: str) -> bool:
        path = Path(rel)
        return path.name.startswith("test_") and path.suffix == ".py"

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        module_slow = _module_marked_slow(src.tree)

        def check_sim_configs(node: ast.AST, slow: bool) -> None:
            """Flag long-duration SimConfig literals outside slow scope."""
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                func = call.func
                name = (func.id if isinstance(func, ast.Name)
                        else func.attr if isinstance(func, ast.Attribute)
                        else None)
                if name not in _SIM_CONFIG_NAMES:
                    continue
                for kw in call.keywords:
                    if kw.arg != "duration_s":
                        continue
                    v = kw.value
                    if (isinstance(v, ast.Constant)
                            and isinstance(v.value, (int, float))
                            and v.value > SIM_TIER1_DURATION_MAX_S
                            and not slow):
                        findings.append(self.finding(
                            src, call,
                            f"SimConfig(duration_s={v.value}) runs a "
                            f"{v.value}s wall-clock sim workload — more "
                            f"than {SIM_TIER1_DURATION_MAX_S:.0f}s belongs "
                            "under @pytest.mark.slow",
                        ))

        def visit(body, class_slow: bool) -> None:
            for node in body:
                if isinstance(node, ast.ClassDef):
                    cls_slow = class_slow or any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    visit(node.body, cls_slow)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn_slow = any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    slow = fn_slow or class_slow or module_slow
                    # Fixtures and helpers count too: whatever function
                    # hosts the long sim, tier-1 pays its wall clock.
                    check_sim_configs(node, slow)
                    if not node.name.startswith("test_"):
                        continue
                    hints = [h for h in SLOW_NAME_HINTS if h in node.name]
                    if not hints:
                        continue
                    if not slow:
                        findings.append(self.finding(
                            src, node,
                            f"{node.name} looks like a soak (name hints: "
                            f"{hints}) but lacks @pytest.mark.slow",
                        ))
                else:
                    # A compound statement (an `if HAVE_JAX:` guard, a
                    # try/except import shim) can nest whole test
                    # functions that carry their own decorators — recurse
                    # into its blocks so those markers are read, instead
                    # of blanket-walking through them.
                    blocks = ("body", "orelse", "finalbody", "handlers")
                    nested: List[ast.stmt] = []
                    for field in ("body", "orelse", "finalbody"):
                        nested.extend(getattr(node, field, None) or [])
                    for handler in getattr(node, "handlers", None) or []:
                        nested.extend(handler.body)
                    if nested:
                        visit(nested, class_slow)
                        # Header expressions (an `if` test, `with` items)
                        # are outside the blocks — scan them here.
                        for field, value in ast.iter_fields(node):
                            if field in blocks:
                                continue
                            for v in (value if isinstance(value, list)
                                      else [value]):
                                if isinstance(v, ast.AST):
                                    check_sim_configs(
                                        v, class_slow or module_slow
                                    )
                    else:
                        # Simple statements (e.g. a shared config
                        # constant) inherit the enclosing scope's mark.
                        check_sim_configs(node, class_slow or module_slow)

        visit(src.tree.body, class_slow=False)
        return findings


def audit(tests_dir) -> List[str]:
    """Back-compat API for `scripts/audit_markers.py` and
    `tests/test_marker_audit.py`: violation strings, old format."""
    rule = SlowMarkerRule()
    out: List[str] = []
    for path in sorted(Path(tests_dir).glob("test_*.py")):
        src = Source(path, root=Path(tests_dir))
        for f in rule.check(src):
            if not src.suppressed(f.rule, f.line):
                out.append(f"{path.name}::{f.message}")
    return out
