"""slow-marker: soak-shaped tests must carry `@pytest.mark.slow`.

The original repo-native rule (previously `scripts/audit_markers.py`, now a
thin shim over this module): tier-1 runs `pytest -m 'not slow'` under a
hard timeout, so ONE unmarked soak blows the whole budget. Any test
function whose name advertises a long-running shape (`soak`, `sustained`,
`stress_many`) must be marked slow — directly, on its class, or via a
module-level `pytestmark`.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List

from ..core import Finding, Rule, Source, register

# Name fragments that mean "this test is a soak, not a unit test".
SLOW_NAME_HINTS = ("soak", "sustained", "stress_many")


def _is_slow_mark(node: ast.expr) -> bool:
    """True for `pytest.mark.slow` / `mark.slow` (bare or called)."""
    if isinstance(node, ast.Call):
        node = node.func
    return isinstance(node, ast.Attribute) and node.attr == "slow"


def _module_marked_slow(tree: ast.Module) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            if "pytestmark" in targets:
                values = (
                    stmt.value.elts
                    if isinstance(stmt.value, (ast.List, ast.Tuple))
                    else [stmt.value]
                )
                if any(_is_slow_mark(v) for v in values):
                    return True
    return False


@register
class SlowMarkerRule(Rule):
    name = "slow-marker"
    description = (
        "test whose name advertises a soak shape (soak/sustained/"
        "stress_many) lacks @pytest.mark.slow — it would blow the tier-1 "
        "timeout"
    )

    def applies_to(self, rel: str) -> bool:
        path = Path(rel)
        return path.name.startswith("test_") and path.suffix == ".py"

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        module_slow = _module_marked_slow(src.tree)

        def visit(body, class_slow: bool) -> None:
            for node in body:
                if isinstance(node, ast.ClassDef):
                    cls_slow = class_slow or any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    visit(node.body, cls_slow)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if not node.name.startswith("test_"):
                        continue
                    hints = [h for h in SLOW_NAME_HINTS if h in node.name]
                    if not hints:
                        continue
                    fn_slow = any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    if not (fn_slow or class_slow or module_slow):
                        findings.append(self.finding(
                            src, node,
                            f"{node.name} looks like a soak (name hints: "
                            f"{hints}) but lacks @pytest.mark.slow",
                        ))

        visit(src.tree.body, class_slow=False)
        return findings


def audit(tests_dir) -> List[str]:
    """Back-compat API for `scripts/audit_markers.py` and
    `tests/test_marker_audit.py`: violation strings, old format."""
    rule = SlowMarkerRule()
    out: List[str] = []
    for path in sorted(Path(tests_dir).glob("test_*.py")):
        src = Source(path, root=Path(tests_dir))
        for f in rule.check(src):
            if not src.suppressed(f.rule, f.line):
                out.append(f"{path.name}::{f.message}")
    return out
