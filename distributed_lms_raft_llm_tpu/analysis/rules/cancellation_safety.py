"""cancellation-safety: async cleanup must survive task cancellation.

Incident class: cancellation is asyncio's only structured teardown
signal — eviction drains, hedged-send losers, shutdown paths all rely on
``CancelledError`` propagating promptly and cleanup still running. Three
lexical shapes quietly break that contract:

- **await in finally** — when the task is being cancelled, the
  ``finally`` block runs with the cancellation pending; a plain
  ``await`` there can be interrupted by a second ``CancelledError`` and
  the rest of the cleanup never executes (half-closed sockets, leaked
  slots). Allowed forms: ``await asyncio.shield(...)`` (explicitly
  protected), ``await asyncio.wait_for(...)`` (bounded, interruption
  acknowledged), and the reap idiom — ``t.cancel()`` earlier in the same
  ``finally`` followed by ``await asyncio.gather/wait(...)`` (collecting
  tasks you just cancelled is exactly how cleanup should look).
- **swallowing CancelledError** — a bare ``except:``, ``except
  BaseException:``, or ``except (asyncio.)CancelledError:`` whose body
  never re-raises eats the cancellation; the caller's ``await
  task`` then hangs or the task zombies on. (``except Exception`` is
  fine: ``CancelledError`` derives from ``BaseException`` since 3.8.)
  Exempt: the canceller-absorb idiom — *this* function called
  ``.cancel()`` earlier and the try body awaits the task; absorbing the
  CancelledError you yourself injected is the textbook reap
  (``t.cancel(); try: await t; except CancelledError: pass``).
- **cancel without await** — ``t.cancel()`` only *requests*
  cancellation; until someone awaits the task (or gathers it), the
  ``CancelledError`` has not been delivered, cleanup has not run, and
  exceptions vanish. A function that cancels and never awaits anything
  that could reap the task leaks it. Flagged only for receivers
  provably tasks — assigned from ``create_task``/``ensure_future`` in
  the same function; ``.cancel()`` on values of unknown type (params,
  attributes, non-task objects with their own sync ``cancel()``) is
  skipped rather than guessed at.

The checks run lexically inside ``async def`` bodies only (nested sync
defs excluded): sync code cannot await the tasks it cancels, and
cancellation semantics are an event-loop contract. The await-in-finally
check additionally skips ``tests/`` — test coroutines run to completion
under ``asyncio.run`` with no canceller, so their ``finally`` blocks
never race a pending CancelledError.

Sanction deliberate exceptions (a span that must close before re-raise,
fire-and-forget cancels at interpreter shutdown) in place with
``# lint: disable=cancellation-safety`` and a reason.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, Rule, Source, register
from ..project import _dotted


def _own_nodes(body: List[ast.stmt]) -> List[ast.AST]:
    """All nodes under `body`, excluding nested function/lambda bodies.

    A nested def is opaque wherever it appears — as a child node or as a
    statement sitting directly in `body` (e.g. a local helper coroutine
    defined inside a ``finally``).
    """
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _tail(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


_SHIELDED = {"shield", "wait_for"}
_REAPERS = {"gather", "wait"}
_SPAWNERS = {"create_task", "ensure_future"}


def _handler_names(type_expr: Optional[ast.expr]) -> List[str]:
    if type_expr is None:
        return [""]  # bare except
    if isinstance(type_expr, ast.Tuple):
        return [_dotted(e) for e in type_expr.elts]
    return [_dotted(type_expr)]


def _swallows_cancellation(names: List[str]) -> bool:
    for name in names:
        if name == "":
            return True
        if name == "BaseException" or _tail(name) == "CancelledError":
            return True
    return False


@register
class CancellationSafetyRule(Rule):
    name = "cancellation-safety"
    description = (
        "async cleanup hazards: await in finally without shield/timeout, "
        "CancelledError swallowed without re-raise, .cancel() on a task "
        "that is never awaited"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                findings.extend(self._check_async_fn(src, node))
        return findings

    def _check_async_fn(
        self, src: Source, fn: ast.AsyncFunctionDef
    ) -> List[Finding]:
        findings: List[Finding] = []
        own = _own_nodes(fn.body)
        in_tests = src.rel.startswith("tests/") or "/tests/" in src.rel
        cancel_lines = [
            node.lineno for node in own
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "cancel"
        ]
        for node in own:
            if isinstance(node, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                if not in_tests:
                    findings.extend(self._check_finally(src, node))
                findings.extend(
                    self._check_handlers(src, node, cancel_lines)
                )
        findings.extend(self._check_unawaited_cancels(src, fn, own))
        return findings

    # --------------------------------------------------- await in finally

    def _check_finally(self, src: Source, node: ast.Try) -> List[Finding]:
        findings: List[Finding] = []
        cancelled_something = False
        for stmt in node.finalbody:
            for sub in _own_nodes([stmt]):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "cancel":
                    cancelled_something = True
                if not isinstance(sub, ast.Await):
                    continue
                value = sub.value
                tail = ""
                if isinstance(value, ast.Call):
                    tail = _tail(_dotted(value.func))
                if tail in _SHIELDED:
                    continue
                if tail in _REAPERS and cancelled_something:
                    continue  # the cancel-then-reap cleanup idiom
                findings.append(self.finding(
                    src, sub,
                    "await in `finally` of an async function without "
                    "asyncio.shield/wait_for — if this task is being "
                    "cancelled, the await can be interrupted and the "
                    "rest of the cleanup never runs; wrap it in "
                    "asyncio.shield(...) (must-complete cleanup) or "
                    "asyncio.wait_for(..., timeout) (bounded best "
                    "effort)",
                ))
        return findings

    # --------------------------------------------- swallowed cancellation

    def _check_handlers(
        self, src: Source, node: ast.Try, cancel_lines: List[int]
    ) -> List[Finding]:
        try_awaits = any(
            isinstance(sub, ast.Await) for sub in _own_nodes(node.body)
        )
        findings: List[Finding] = []
        for handler in node.handlers:
            names = _handler_names(handler.type)
            if not _swallows_cancellation(names):
                continue
            reraises = any(
                isinstance(sub, ast.Raise)
                for sub in _own_nodes(handler.body)
            )
            if reraises:
                continue
            if try_awaits and any(
                line < handler.lineno for line in cancel_lines
            ):
                # Canceller-absorb: this function cancelled the task and
                # the try body awaits it — swallowing the CancelledError
                # it injected is the reap, not a lost cancellation.
                continue
            what = (
                "bare `except:`" if names == [""] else
                f"`except {', '.join(n for n in names if n)}:`"
            )
            findings.append(self.finding(
                src, handler,
                f"{what} swallows CancelledError without re-raising — "
                "the task keeps running after cancellation and the "
                "canceller's `await task` may hang; catch Exception "
                "instead (CancelledError derives from BaseException), "
                "or re-raise after cleanup",
            ))
        return findings

    # ----------------------------------------------- cancel without await

    def _check_unawaited_cancels(
        self, src: Source, fn: ast.AsyncFunctionDef, own: List[ast.AST]
    ) -> List[Finding]:
        cancels: List[ast.Call] = []
        awaited: Set[str] = set()
        spawned: Set[str] = set()
        has_reaper = False
        for node in own:
            if isinstance(node, ast.Await):
                value = node.value
                if isinstance(value, ast.Call):
                    if _tail(_dotted(value.func)) in _REAPERS:
                        has_reaper = True
                for sub in ast.walk(value):
                    if isinstance(sub, (ast.Name, ast.Attribute)):
                        dotted = _dotted(sub)
                        if dotted:
                            awaited.add(dotted)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                func = node.value.func
                spawner = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else ""
                )
                if spawner in _SPAWNERS:
                    for target in node.targets:
                        dotted = _dotted(target)
                        if dotted:
                            spawned.add(dotted)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "cancel":
                receiver = _dotted(node.func.value)
                if receiver:
                    cancels.append(node)
        if has_reaper:
            # One gather/wait reaps every task this function cancelled.
            return []
        findings: List[Finding] = []
        for call in cancels:
            receiver = _dotted(call.func.value)  # type: ignore[attr-defined]
            if receiver not in spawned:
                # Unknown type — could be a non-task with a sync
                # cancel(); only provably-spawned tasks are flagged.
                continue
            if receiver in awaited:
                continue
            findings.append(self.finding(
                src, call,
                f"{receiver}.cancel() but {receiver} is never awaited in "
                "this function — cancel() only requests cancellation; "
                "until the task is awaited (or gathered with "
                "return_exceptions=True) its cleanup has not run and "
                "its exceptions vanish; await it, or hand it to a "
                "reaper that does",
            ))
        return findings
