"""wire-taint: untrusted wire fields must pass a sanitizer before a sink.

Incident class: PR 16 put a real trust boundary into the router — client
metadata is unsigned, router->member metadata carries an HMAC
(`sign_router_metadata` / `_signed_md`, verified with
`hmac.compare_digest`). Everything security-relevant that arrives over
the wire must cross that boundary through a sanctioner:

- **trust metadata** (`x-lms-*` keys) may only be read through
  ``_signed_md`` (or the router's own ``_InnerContext`` /
  ``_forced_auth`` shims). Reading ``x-lms-group`` out of raw
  ``invocation_metadata()`` — directly, via a dict, in a ``for k, v``
  scan, or laundered through a generic raw reader such as
  ``_metadata_get`` — lets any client steer group routing or forge the
  router leg. (``x-lms-user`` is the documented *unsigned hint* used
  only to pin sticky routing; it is exempt.)
- **request fields** must not reach filesystem path construction
  (``open``, ``os.path.join``, ``os.remove``...) without a sanitizing
  hop; the blob store's ``_resolve`` escape-guard is the sanctioned
  path sink.
- **secret comparisons** (password hashes, tokens, signatures) must use
  ``hmac.compare_digest`` — ``==`` on attacker-influenced digests is a
  timing oracle.

Taint propagates through straight-line assignments inside a function and
one forwarding hop into a project-resolvable callee (a tainted argument
taints the matching parameter); deeper laundering is out of scope and is
instead constrained by keeping the sanctioner list short and named.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ..core import Finding, register
from ..project import FunctionInfo, ModuleInfo, Project, ProjectRule, _dotted

DEFAULT_WATCH = ("distributed_lms_raft_llm_tpu/lms/",)

#: Functions allowed to touch raw invocation_metadata: the verifier, the
#: forced-auth gate (which checks the router-leg marker first), the
#: router's context shims, and the signer itself.
SANCTIONED_FUNCS: FrozenSet[str] = frozenset({
    "_signed_md", "_forced_auth", "sign_router_metadata",
    "invocation_metadata",
})

#: Metadata VALUES that are documented unsigned hints (sticky-routing
#: only, never trust decisions).
EXEMPT_KEYS: FrozenSet[str] = frozenset({"x-lms-user"})

_WIRE_PREFIX = "x-lms-"

#: Call names whose result is a secret digest/signature.
_HASH_FNS = frozenset({
    "hash_password", "pbkdf2_hmac", "sign_query", "sign_router_metadata",
    "hexdigest",
})

#: Identifier terminals that denote stored/presented secrets.
_SECRET_TERMS = frozenset({
    "password", "password_hash", "token", "auth_token", "secret",
    "signature", "router_sig", "sig",
})

#: Filesystem path sinks for request-field taint.
_PATH_SINKS = frozenset({
    "open", "os.path.join", "os.remove", "os.unlink", "os.makedirs",
    "os.rename", "os.replace", "os.rmdir", "os.open",
})

#: A call through one of these names sanitizes its argument.
_SANITIZERS = ("sanitize", "secure_filename", "basename")


def _module_consts(mod: ModuleInfo) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


@register
class WireTaintRule(ProjectRule):
    name = "wire-taint"
    description = (
        "untrusted wire fields (raw gRPC metadata, request fields) must "
        "pass the sanctioner (_signed_md, blob-store resolve, "
        "hmac.compare_digest) before trust decisions, paths, or secret "
        "comparisons"
    )

    def __init__(self, watch_prefixes: Sequence[str] = DEFAULT_WATCH):
        self.watch_prefixes = tuple(watch_prefixes)

    # ------------------------------------------------------------ plumbing

    def _watched(self, rel: str) -> bool:
        return any(rel.startswith(p) for p in self.watch_prefixes)

    def _key_value(
        self, project: Project, mod: ModuleInfo, node: ast.expr,
        consts: Dict[str, Dict[str, str]],
    ) -> Optional[str]:
        """The string a metadata-key expression denotes, if visible."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            local = consts.setdefault(mod.rel, _module_consts(mod))
            if node.id in local:
                return local[node.id]
            imp = mod.imports.get(node.id)
            if imp is not None and imp[0] == "sym":
                other = project.modules.get(imp[1])
                if other is not None:
                    omap = consts.setdefault(other.rel, _module_consts(other))
                    return omap.get(imp[2])
        return None

    def _sensitive(self, value: Optional[str]) -> bool:
        return (
            value is not None
            and value.startswith(_WIRE_PREFIX)
            and value not in EXEMPT_KEYS
        )

    @staticmethod
    def _raw_meta_call(node: ast.expr) -> bool:
        """Does this expression contain a raw invocation_metadata() read?"""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "invocation_metadata":
                return True
        return False

    # -------------------------------------------------------------- check

    def check_project(self, project: Project) -> List[Finding]:
        consts: Dict[str, Dict[str, str]] = {}
        raw_readers = self._raw_readers(project)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()

        def emit(rel: str, line: int, message: str) -> None:
            if (rel, line) in seen:
                return
            src = project.sources.get(rel)
            if src is None:  # pragma: no cover - functions come from sources
                return
            seen.add((rel, line))
            findings.append(self.finding(src, line, message))

        for fn in project.functions.values():
            if not self._watched(fn.rel):
                continue
            if fn.name in SANCTIONED_FUNCS:
                continue
            self._check_function(
                project, fn, consts, raw_readers, emit,
                pre_tainted=frozenset(), hop=True,
            )
        return findings

    def _raw_readers(self, project: Project) -> Set[str]:
        """Project functions whose body reads raw invocation_metadata —
        calling one with an x-lms key is laundering, not sanitizing."""
        out: Set[str] = set()
        for qname, fn in project.functions.items():
            if fn.name in SANCTIONED_FUNCS:
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "invocation_metadata":
                    out.add(qname)
                    break
        return out

    # ---------------------------------------------------- per-function scan

    def _check_function(
        self, project: Project, fn: FunctionInfo,
        consts: Dict[str, Dict[str, str]],
        raw_readers: Set[str], emit,
        *, pre_tainted: FrozenSet[str], hop: bool,
    ) -> None:
        mod = project.modules[fn.rel]

        tainted = self._tainted_locals(fn, pre_tainted)
        path_tainted = self._path_tainted_locals(project, mod, fn)

        def is_tainted(expr: ast.expr) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in tainted
            return self._raw_meta_call(expr)

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                self._check_call(
                    project, mod, fn, node, consts, raw_readers,
                    tainted, path_tainted, is_tainted, emit, hop,
                )
            elif isinstance(node, ast.Subscript):
                key = self._key_value(project, mod, node.slice, consts)
                if self._sensitive(key) and is_tainted(node.value):
                    emit(fn.rel, node.lineno, self._trust_msg(key))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if is_tainted(node.iter):
                    self._check_meta_scan(
                        project, mod, fn, node, consts, emit
                    )
            elif isinstance(node, ast.Compare) and hop:
                # Secret comparisons only flagged in the outer pass — a
                # forwarded hop re-walking them would double-report.
                self._check_secret_compare(fn, node, emit)

    def _tainted_locals(
        self, fn: FunctionInfo, pre_tainted: FrozenSet[str]
    ) -> Set[str]:
        tainted: Set[str] = set(pre_tainted)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                value_bad = self._raw_meta_call(node.value) or any(
                    isinstance(sub, ast.Name) and sub.id in tainted
                    for sub in ast.walk(node.value)
                )
                if not value_bad:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name) \
                            and target.id not in tainted:
                        tainted.add(target.id)
                        changed = True
        return tainted

    def _path_tainted_locals(
        self, project: Project, mod: ModuleInfo, fn: FunctionInfo
    ) -> Set[str]:
        """Locals derived from request.<field> without a sanitizing hop."""
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                if self._sanitizer_call(node.value):
                    continue
                if not self._request_derived(node.value, tainted):
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name) \
                            and target.id not in tainted:
                        tainted.add(target.id)
                        changed = True
        return tainted

    @staticmethod
    def _sanitizer_call(expr: ast.expr) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        dotted = _dotted(expr.func)
        tail = dotted.rsplit(".", 1)[-1] if dotted else ""
        return any(s in tail for s in _SANITIZERS)

    @staticmethod
    def _request_derived(expr: ast.expr, tainted: Set[str]) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "request":
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    # ------------------------------------------------------------ detectors

    def _trust_msg(self, key: Optional[str]) -> str:
        return (
            f"trust metadata {key!r} read from RAW invocation_metadata — "
            "any client can set it. Route the read through _signed_md() "
            "so only HMAC-signed router metadata is honored."
        )

    def _check_call(
        self, project: Project, mod: ModuleInfo, fn: FunctionInfo,
        node: ast.Call, consts: Dict[str, Dict[str, str]],
        raw_readers: Set[str], tainted: Set[str], path_tainted: Set[str],
        is_tainted, emit, hop: bool,
    ) -> None:
        dotted = _dotted(node.func)
        # .get(<x-lms key>) on a raw-metadata-derived mapping.
        if isinstance(node.func, ast.Attribute) and node.func.attr == "get" \
                and node.args:
            key = self._key_value(project, mod, node.args[0], consts)
            if self._sensitive(key) and is_tainted(node.func.value):
                emit(fn.rel, node.lineno, self._trust_msg(key))
                return
        # Filesystem path sinks fed by request fields.
        if dotted in _PATH_SINKS:
            for arg in node.args:
                if self._sanitizer_call(arg):
                    continue
                if self._request_derived(arg, path_tainted):
                    emit(
                        fn.rel, node.lineno,
                        f"request field reaches path sink {dotted}() "
                        "without a sanitizing hop — route through the "
                        "blob store's _resolve (escape-guarded) or a "
                        "sanitize_*() helper.",
                    )
                    return
        callee = project.resolve_call(mod, node.func, fn.class_name, fn)
        if callee is None:
            return
        # Laundering through a generic raw reader: _metadata_get(ctx, KEY).
        if callee.qname in raw_readers:
            for arg in node.args:
                key = self._key_value(project, mod, arg, consts)
                if self._sensitive(key):
                    emit(
                        fn.rel, node.lineno,
                        f"trust metadata {key!r} fetched via "
                        f"{callee.name}(), which reads RAW "
                        "invocation_metadata — a sanctioner bypass. Use "
                        "_signed_md() for x-lms-* trust keys.",
                    )
                    return
        # One forwarding hop: tainted argument -> callee parameter.
        if hop and self._watched(callee.rel) \
                and callee.name not in SANCTIONED_FUNCS:
            params = [
                a.arg for a in callee.node.args.args  # type: ignore[attr-defined]
                if a.arg != "self"
            ]
            forwarded: Set[str] = set()
            args = list(node.args)
            for i, arg in enumerate(args):
                if i < len(params) and is_tainted(arg):
                    forwarded.add(params[i])
            if forwarded:
                self._check_function(
                    project, callee, consts, raw_readers, emit,
                    pre_tainted=frozenset(forwarded), hop=False,
                )

    def _check_meta_scan(
        self, project: Project, mod: ModuleInfo, fn: FunctionInfo,
        loop: ast.AST, consts: Dict[str, Dict[str, str]], emit,
    ) -> None:
        """`for k, v in <raw metadata>` comparing k to an x-lms key."""
        for node in ast.walk(loop):
            if not isinstance(node, ast.Compare):
                continue
            for side in [node.left] + list(node.comparators):
                key = self._key_value(project, mod, side, consts)
                if self._sensitive(key):
                    emit(fn.rel, node.lineno, self._trust_msg(key))
                    break

    def _check_secret_compare(
        self, fn: FunctionInfo, node: ast.Compare, emit
    ) -> None:
        if not all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            return
        sides = [node.left] + list(node.comparators)
        # `password == ""` style emptiness probes are not timing oracles.
        if any(isinstance(s, ast.Constant) for s in sides):
            return
        if any(self._secretish(s) for s in sides):
            emit(
                fn.rel, node.lineno,
                "secret compared with ==/!= — a timing oracle on "
                "attacker-influenced input. Use hmac.compare_digest().",
            )

    @staticmethod
    def _secretish(expr: ast.expr) -> bool:
        if isinstance(expr, ast.Call):
            dotted = _dotted(expr.func)
            if dotted and dotted.rsplit(".", 1)[-1] in _HASH_FNS:
                return True
            return False
        terminal = ""
        if isinstance(expr, ast.Name):
            terminal = expr.id
        elif isinstance(expr, ast.Attribute):
            terminal = expr.attr
        elif isinstance(expr, ast.Subscript) \
                and isinstance(expr.slice, ast.Constant) \
                and isinstance(expr.slice.value, str):
            terminal = expr.slice.value
        return terminal in _SECRET_TERMS
