"""tracer-hygiene: no Python control flow on traced values in jitted code.

Inside a `jax.jit`-traced function, a Python `if`/`while`/`assert`/`bool()`
on a device value either raises a ConcretizationTypeError at trace time
(best case) or — when the value is concrete during tracing, e.g. a shape
probe that later becomes a tracer — silently bakes one branch into the
compiled program and re-traces per value (the recompile-per-request family
again, one level down from the PartitionSpec spelling bug).

Detection is module-local and deliberately conservative (near-zero false
positives beats exhaustive):

- jit roots: functions passed to `jax.jit` / `jit` / `shard_map` / `pmap`
  in this module (unwrapping `partial(...)`), plus functions nested inside
  a jit root (scan/fori bodies);
- traced locals: names assigned from `jnp.*` / `jax.lax.*` / `jax.nn.*` /
  `jax.random.*` calls, or from expressions over already-traced names —
  a simple transitive closure. Function parameters and attribute reads
  are NOT assumed traced (config/static attributes dominate there).
- flagged: `if` / `while` / ternary / `assert` tests that reference a
  traced local or contain a device-namespace call directly, and
  `bool(...)` over either.

Also flags the unhashable-static-arg footgun: a call to a jitted function
whose `static_argnums` position receives a list/dict/set literal — that is
a guaranteed `TypeError: unhashable type` at the first dispatch.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Rule, Source, register

_JIT_WRAPPERS = {"jit", "shard_map", "pmap"}
_DEVICE_BASES = {"jnp", "lax"}
_JAX_SUBMODULES = {"lax", "nn", "random", "numpy"}


def _callee_name(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _unwrap_partial(expr: ast.expr) -> Optional[str]:
    """The function NAME inside `f`, `partial(f, ...)`, or
    `functools.partial(f, ...)`."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Call) and _callee_name(expr) == "partial":
        if expr.args and isinstance(expr.args[0], ast.Name):
            return expr.args[0].id
    return None


def _is_device_call(node: ast.expr) -> bool:
    """jnp.xxx(...) / lax.xxx(...) / jax.lax.xxx / jax.nn.xxx /
    jax.random.xxx call."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if not isinstance(func, ast.Attribute):
        return False
    base = func.value
    if isinstance(base, ast.Name) and base.id in _DEVICE_BASES:
        return True
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "jax"
        and base.attr in _JAX_SUBMODULES
    ):
        return True
    return False


def _jit_static_info(
    tree: ast.Module,
) -> Tuple[Set[str], Dict[str, Tuple[int, ...]]]:
    """(jit-root function names, {jitted-binding-name: static_argnums})."""
    roots: Set[str] = set()
    statics: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _callee_name(node) not in _JIT_WRAPPERS:
            continue
        if node.args:
            target = _unwrap_partial(node.args[0])
            if target is not None:
                roots.add(target)
        nums: Tuple[int, ...] = ()
        for kw in node.keywords:
            if kw.arg == "static_argnums":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    nums = tuple(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    )
                elif isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, int
                ):
                    nums = (kw.value.value,)
        if nums:
            parent = getattr(node, "parent", None)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        statics[t.id] = nums
                    elif (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        statics[f"self.{t.attr}"] = nums
    return roots, statics


def _traced_locals(fn: ast.AST) -> Set[str]:
    """Transitive closure of locals assigned from device-namespace calls."""
    traced: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if _expr_traced(node.value, traced):
                for t in node.targets:
                    for name in _target_names(t):
                        if name not in traced:
                            traced.add(name)
                            changed = True
    return traced


def _target_names(t: ast.expr) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _is_identity_test(expr: ast.expr) -> bool:
    """`x is None` / `x is not None`: identity never reads a tracer's
    value, so these are static under trace even on traced names."""
    return isinstance(expr, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
    )


def _expr_traced(expr: ast.expr, traced: Set[str]) -> bool:
    if _is_identity_test(expr):
        return False
    for node in ast.walk(expr):
        if _is_device_call(node):
            return True
        if isinstance(node, ast.Name) and node.id in traced:
            return True
    return False


@register
class TracerHygieneRule(Rule):
    name = "tracer-hygiene"
    description = (
        "Python control flow (if/while/assert/bool) over a traced value "
        "inside jit-reachable code, or a list/dict/set literal passed in a "
        "static_argnums position — trace-time errors and silent "
        "per-value recompiles"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        roots, statics = _jit_static_info(src.tree)
        for node in ast.walk(src.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in roots
            ):
                findings.extend(self._check_traced_fn(src, node))
        findings.extend(self._check_static_args(src, statics))
        return findings

    def _check_traced_fn(self, src: Source, fn: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        traced = _traced_locals(fn)
        for node in ast.walk(fn):
            test: Optional[ast.expr] = None
            what = ""
            if isinstance(node, (ast.If, ast.While)):
                test, what = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.IfExp):
                test, what = node.test, "conditional expression"
            elif isinstance(node, ast.Assert):
                test, what = node.test, "assert"
            elif isinstance(node, ast.Call) and _callee_name(node) == "bool":
                if node.args and _expr_traced(node.args[0], traced):
                    findings.append(self.finding(
                        src, node,
                        "bool() over a traced value in jit-reachable code "
                        "— concretizes the tracer (trace error or silent "
                        "per-value recompile); use jnp.where / lax.cond",
                    ))
                continue
            if test is not None and _expr_traced(test, traced):
                findings.append(self.finding(
                    src, node,
                    f"Python {what} on a traced value in jit-reachable "
                    "code — the branch is baked in at trace time; use "
                    "jnp.where / lax.cond / lax.while_loop",
                ))
        return findings

    def _check_static_args(
        self, src: Source, statics: Dict[str, Tuple[int, ...]]
    ) -> List[Finding]:
        findings: List[Finding] = []
        if not statics:
            return findings
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            key = None
            if isinstance(func, ast.Name):
                key = func.id
            elif (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                key = f"self.{func.attr}"
            nums = statics.get(key or "")
            if not nums:
                continue
            for i in nums:
                if i < len(node.args) and isinstance(
                    node.args[i], (ast.List, ast.Dict, ast.Set)
                ):
                    findings.append(self.finding(
                        src, node,
                        f"static_argnums position {i} of {key} receives an "
                        "unhashable literal (list/dict/set) — guaranteed "
                        "TypeError at dispatch; pass a tuple or hashable "
                        "config object",
                    ))
        return findings
