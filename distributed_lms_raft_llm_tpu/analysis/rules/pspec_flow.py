"""pspec-flow: one MEANING per named state plane, across every producer.

`canonical-pspec` (PR 3) closed the spelling half of the PR-2 recompile
incident: `P(None, None)` may no longer be written where `P()` is meant.
This rule closes the semantic half. Since the paged engine went
mesh-native the policy is a *plane table* — a module-level literal dict
(`parallel/partition.PAGED_PLANE_SPECS`) mapping each named plane to its
ONE sharding (KV planes tp-sharded over heads, host planes replicated) —
so the invariant is two-layered:

- a producer of a plane the table DECLARES must land it under exactly the
  table's spec: a `device_put` that disagrees is a real layout divergence
  — every consuming program would either recompile per producer (when
  GSPMD tolerates it) or reshard per dispatch (when it doesn't), and both
  spellings can be individually canonical, so the lexical rule stays
  silent;
- producers of UNdeclared planes must at least agree with each other
  (the original pairwise invariant, kept for engine state that predates
  or sits outside the table).

Mechanics (analysis/absint.py): every `jax.device_put` of a named plane
(`state.tok`, `state.cache.k`, ...) in the engine modules is collected
with its spec evaluated to a canonical meaning — helper functions
(`_plane_spec`) resolved through their returns, nested helpers
(`_canon_state.put`) resolved by binding call-site arguments, literal
plane names flowed into spec-table subscripts, `P(...)` literals
normalized by dropping trailing Nones. Unresolvable specs contribute
nothing (missing resolution loses findings, never invents them).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from .. import absint
from ..core import Finding, register
from ..project import Project, ProjectRule


@register
class PSpecFlowRule(ProjectRule):
    name = "pspec-flow"
    description = (
        "a state plane is device_put under a sharding that disagrees with "
        "the plane table (or, for undeclared planes, under two semantically "
        "different PartitionSpecs across producers) — the jit caches key "
        "per producer and the dispatch boundary pays a recompile or a "
        "reshard (the PR-2 class, beyond spelling)"
    )

    def __init__(
        self, watch_prefixes: Sequence[str] = (absint.ENGINE_PREFIX,)
    ):
        self.watch_prefixes = tuple(watch_prefixes)

    def check_project(self, project: Project) -> List[Finding]:
        puts = absint.collect_plane_puts(project, self.watch_prefixes)
        # plane -> (declaring table name, canonical spec). Tables are
        # policy wherever they live (the real one is in parallel/, outside
        # the watched producer modules).
        declared: Dict[str, Tuple[str, str]] = {}
        for tname, table in sorted(absint.plane_tables(project).items()):
            for plane, spec in table.items():
                if isinstance(spec, str):
                    declared.setdefault(plane, (tname, spec))
        by_plane: Dict[str, List[Tuple[absint.PlanePut, str]]] = {}
        findings: List[Finding] = []
        seen = set()
        for put in puts:
            src = project.sources.get(put.rel)
            if src is not None and src.suppressed(self.name, put.line):
                # A suppressed producer is a sanctioned one-off (documented
                # reshard): it neither reports nor counts as a conflicting
                # producer against the plane's remaining sites.
                continue
            if not isinstance(put.spec, str):
                continue
            decl = declared.get(put.plane)
            if decl is None:
                by_plane.setdefault(put.plane, []).append((put, put.spec))
                continue
            tname, want = decl
            if put.spec == want:
                continue
            key = (put.rel, put.line, put.plane)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                rule=self.name, path=put.rel, line=put.line,
                message=(
                    f"state plane '{put.plane}' is device_put under "
                    f"{put.spec}, but the plane table {tname} declares "
                    f"{want} — every producer must land a named plane "
                    f"under the table's ONE sharding so all programs "
                    f"share one jit-cache key (see paged._plane_spec)"
                ),
            ))
        for plane, sites in sorted(by_plane.items()):
            specs = sorted({spec for _, spec in sites})
            if len(specs) <= 1:
                continue
            for put, spec in sites:
                key = (put.rel, put.line, plane)
                if key in seen:
                    continue
                seen.add(key)
                src = project.sources.get(put.rel)
                if src is None:
                    continue
                findings.append(Finding(
                    rule=self.name, path=put.rel, line=put.line,
                    message=(
                        f"state plane '{plane}' is produced under "
                        f"{len(specs)} different shardings "
                        f"({', '.join(specs)}); this site uses {spec} — "
                        "pick ONE spec per plane so every producer shares "
                        "one jit-cache key (see paged._plane_spec)"
                    ),
                ))
        return findings
