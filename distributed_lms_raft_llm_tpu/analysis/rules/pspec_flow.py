"""pspec-flow: one MEANING per state plane, across every producer.

`canonical-pspec` (PR 3) closed the spelling half of the PR-2 recompile
incident: `P(None, None)` may no longer be written where `P()` is meant.
This rule closes the semantic half: a SlotState plane produced under one
sharding in `_init_state` and respelled under a *different* sharding at
the dispatch boundary is a real layout divergence — every step program
would either recompile per producer (when GSPMD tolerates it) or reshard
per dispatch (when it doesn't), and both spellings can be individually
canonical, so the lexical rule stays silent.

Mechanics (analysis/absint.py): every `jax.device_put` of a named plane
(`state.tok`, `state.cache.length`, ...) in the engine modules is
collected with its spec evaluated to a canonical meaning — helper
functions (`_state_spec`) resolved through their returns, nested helpers
(`_canon_state.put`) resolved by binding call-site arguments, `P(...)`
literals normalized by dropping trailing Nones. Planes whose resolved
specs disagree get a finding at EVERY producing site, naming the
conflict; unresolvable specs contribute nothing (missing resolution loses
findings, never invents them).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from .. import absint
from ..core import Finding, register
from ..project import Project, ProjectRule


@register
class PSpecFlowRule(ProjectRule):
    name = "pspec-flow"
    description = (
        "a state plane is device_put under two semantically different "
        "PartitionSpecs across the engine's producers — the jit caches key "
        "per producer and the dispatch boundary pays a recompile or a "
        "reshard (the PR-2 class, beyond spelling)"
    )

    def __init__(
        self, watch_prefixes: Sequence[str] = (absint.ENGINE_PREFIX,)
    ):
        self.watch_prefixes = tuple(watch_prefixes)

    def check_project(self, project: Project) -> List[Finding]:
        puts = absint.collect_plane_puts(project, self.watch_prefixes)
        by_plane: Dict[str, List[Tuple[absint.PlanePut, str]]] = {}
        for put in puts:
            src = project.sources.get(put.rel)
            if src is not None and src.suppressed(self.name, put.line):
                # A suppressed producer is a sanctioned one-off (documented
                # reshard): it neither reports nor counts as a conflicting
                # producer against the plane's remaining sites.
                continue
            if isinstance(put.spec, str):
                by_plane.setdefault(put.plane, []).append((put, put.spec))
        findings: List[Finding] = []
        seen = set()
        for plane, sites in sorted(by_plane.items()):
            specs = sorted({spec for _, spec in sites})
            if len(specs) <= 1:
                continue
            for put, spec in sites:
                key = (put.rel, put.line, plane)
                if key in seen:
                    continue
                seen.add(key)
                src = project.sources.get(put.rel)
                if src is None:
                    continue
                findings.append(Finding(
                    rule=self.name, path=put.rel, line=put.line,
                    message=(
                        f"state plane '{plane}' is produced under "
                        f"{len(specs)} different shardings "
                        f"({', '.join(specs)}); this site uses {spec} — "
                        "pick ONE spec per plane so every producer shares "
                        "one jit-cache key (see paged._state_spec)"
                    ),
                ))
        return findings
