"""no-host-sync-in-dispatch: device readbacks in engine hot paths must be
marked as intended.

The paged engine's throughput history is a history of accidental host
syncs: a reap-time `device_get` serialized the loop at ~270 tok/s until
the copies were started asynchronously (engine/paged.step), and chunk=1
dispatch paid a ~100 ms round trip per token. A `.item()`, `float()`,
`np.asarray(...)` or `jax.device_get(...)` dropped into the dispatch path
is invisible in review and costs a full device round trip per call.

This rule flags host-sync constructs in the engine dispatch modules
(`engine/paged.py`, `engine/engine.py`, `engine/draft.py`) unless they sit
inside a `with guards.intended_transfer():` block — the SAME marker the
runtime transfer guard uses (utils/guards.py), so the static rule and the
TPU-side `jax.transfer_guard` assertion enforce one shared set of
sanctioned sync points.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Rule, Source, register

# Modules whose bodies ARE the dispatch hot path.
DISPATCH_MODULES = (
    "engine/paged.py",
    "engine/engine.py",
    "engine/draft.py",
    # The scoring tenant's quantum loop shares the serving chip: a bare
    # .item()/np.asarray there stalls interactive dispatch exactly like
    # a decode-path sync would.
    "engine/scoring.py",
)

_SYNC_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
_NP_SYNC_FUNCS = {"asarray", "array", "copy"}
_NP_MODULE_NAMES = {"np", "numpy"}
_JAX_SYNC_FUNCS = {"device_get"}
_CAST_FUNCS = {"float", "int", "bool"}
_DEVICE_NAMESPACES = {"jnp", "jax", "lax"}


def _inside_intended_transfer(src: Source, node: ast.AST) -> bool:
    for anc in src.parents(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                name = (
                    expr.attr if isinstance(expr, ast.Attribute)
                    else expr.id if isinstance(expr, ast.Name) else ""
                )
                if name == "intended_transfer":
                    return True
    return False


def _is_device_ns_call(node: ast.expr) -> bool:
    """True for jnp.xxx(...) / jax.yyy.xxx(...) call results."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    while isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Name) and func.value.id in _DEVICE_NAMESPACES:
            return True
        func = func.value  # type: ignore[assignment]
    return False


@register
class HostSyncInDispatchRule(Rule):
    name = "no-host-sync-in-dispatch"
    description = (
        "host<->device sync (.item/.tolist/np.asarray/jax.device_get/"
        "float-of-jnp) in an engine dispatch module outside a "
        "`with intended_transfer():` block — every unmarked sync is a "
        "hidden per-step device round trip"
    )

    def applies_to(self, rel: str) -> bool:
        return any(rel.endswith(m) for m in DISPATCH_MODULES)

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            label = self._sync_label(node)
            if label is None:
                continue
            if _inside_intended_transfer(src, node):
                continue
            findings.append(
                self.finding(
                    src,
                    node,
                    f"{label} is a host sync in a dispatch module; wrap the "
                    "intended sync point in `with intended_transfer():` "
                    "(utils/guards.py) or move it off the hot path",
                )
            )
        return findings

    @staticmethod
    def _sync_label(node: ast.Call) -> str | None:
        func = node.func
        if isinstance(func, ast.Attribute):
            # x.item() / x.tolist() / x.block_until_ready()
            if func.attr in _SYNC_ATTR_CALLS and not node.args:
                return f".{func.attr}()"
            # np.asarray(...) / numpy.array(...)
            if (
                func.attr in _NP_SYNC_FUNCS
                and isinstance(func.value, ast.Name)
                and func.value.id in _NP_MODULE_NAMES
            ):
                return f"{func.value.id}.{func.attr}(...)"
            # jax.device_get(...)
            if (
                func.attr in _JAX_SYNC_FUNCS
                and isinstance(func.value, ast.Name)
                and func.value.id == "jax"
            ):
                return "jax.device_get(...)"
        elif isinstance(func, ast.Name):
            if func.id in _JAX_SYNC_FUNCS:
                return f"{func.id}(...)"
            # float(jnp.sum(x)) — a cast forcing a device value to host.
            if (
                func.id in _CAST_FUNCS
                and node.args
                and _is_device_ns_call(node.args[0])
            ):
                return f"{func.id}(<device value>)"
        return None
