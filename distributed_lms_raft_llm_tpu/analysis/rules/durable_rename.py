"""durable-rename: atomic-rename writes in storage modules must be durable.

The bug class (ALICE, OSDI '14): `os.replace(tmp, final)` makes the *name
swap* atomic, but nothing orders the tmp file's DATA ahead of the rename —
after a crash the durable directory entry can point at an empty or partial
file (this repo's instance: an uploaded PDF committed by `_BlobWriter`
without an fsync, lms/persistence.py pre-PR-5). And the rename itself is
only durable once the parent DIRECTORY is fsynced.

So, in the storage modules this rule scopes to, every rename through
`os.replace`/`os.rename` or the `utils.diskfaults.FileSystem` seam
(`fs.replace`/`self.fs.replace`) must, within the same function:

- be PRECEDED by an `fsync` call (of the source file's handle), and
- be FOLLOWED by an `fsync_dir` call (of the destination's parent).

The check is lexical by design (like guarded-by): it cannot prove the
fsync targets the right handle, but it pins the *shape* of every durable
rename so the PR-5 satellite fixes cannot quietly revert. Renames of
already-closed, already-durable files (e.g. quarantining a corrupt WAL to
`*.corrupt`) carry a visible `# lint: disable=durable-rename` with the
reason.

String `.replace(...)` calls are ignored: only receivers that denote the
`os` module or a filesystem seam (`fs`, `_fs`, `self.fs`, `self._fs`)
count as renames.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Rule, Source, register

# The storage modules whose renames carry durability obligations. The
# diskfaults seam itself is excluded: its `replace()` IS the primitive
# this rule audits the callers of.
STORAGE_MODULES = (
    "distributed_lms_raft_llm_tpu/raft/storage.py",
    "distributed_lms_raft_llm_tpu/lms/persistence.py",
    "distributed_lms_raft_llm_tpu/lms/node.py",
)

_RENAME_ATTRS = {"replace", "rename"}
_FS_NAMES = {"fs", "_fs"}


def _is_fs_receiver(expr: ast.expr) -> bool:
    """True for `os`, `fs`, `_fs`, `self.fs`, `self._fs`, `<x>.fs`."""
    if isinstance(expr, ast.Name):
        return expr.id == "os" or expr.id in _FS_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _FS_NAMES
    return False


def _call_attr(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _enclosing_scope(src: Source, node: ast.AST) -> ast.AST:
    for anc in src.parents(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return src.tree  # module-level code


@register
class DurableRenameRule(Rule):
    name = "durable-rename"
    description = (
        "os.replace/os.rename (or fs.replace) in a storage module without "
        "a preceding fsync of the source file or a following parent-"
        "directory fsync — after a crash the rename can survive while the "
        "data (or the rename itself) did not"
    )

    def applies_to(self, rel: str) -> bool:
        return rel in STORAGE_MODULES

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _RENAME_ATTRS:
                continue
            if not _is_fs_receiver(node.func.value):
                continue  # str.replace and friends
            scope = _enclosing_scope(src, node)
            has_fsync_before = False
            has_dirsync_after = False
            for other in ast.walk(scope):
                if not isinstance(other, ast.Call) or other is node:
                    continue
                attr = _call_attr(other)
                if attr == "fsync" and other.lineno <= node.lineno:
                    has_fsync_before = True
                elif attr == "fsync_dir" and other.lineno >= node.lineno:
                    has_dirsync_after = True
            if not has_fsync_before:
                findings.append(self.finding(
                    src, node,
                    f"{ast.unparse(node.func)}() without a preceding fsync "
                    "of the source file in this function: the atomic rename "
                    "can outlive its un-synced contents across a crash, "
                    "leaving a durable name on an empty/partial file — "
                    "fsync the temp file before renaming it",
                ))
            if not has_dirsync_after:
                findings.append(self.finding(
                    src, node,
                    f"{ast.unparse(node.func)}() without a following "
                    "fsync_dir of the destination's parent directory: the "
                    "rename itself is not durable until the directory "
                    "entry is — call fs.fsync_dir(parent) after renaming",
                ))
        return findings
