"""no-orphan-task: every spawned task needs an owner; every coroutine an
await.

Two silent failure modes this rule pins down:

1. Fire-and-forget `asyncio.ensure_future` / `create_task` whose handle is
   dropped. The event loop holds tasks WEAKLY — a dropped handle can be
   garbage-collected mid-flight, and its exception (if it survives long
   enough to raise) is reported to nobody. `raft/grpc_transport._stub`'s
   channel-close task was a live instance. The fix pattern is the one
   `raft/node._pump` uses: keep the handle (list/set/attribute) and detach
   it in a done callback, or `await` it.

2. A bare expression statement calling an `async def` defined in the same
   module/class without `await`: the coroutine object is created, never
   scheduled, and the call silently does nothing (Python warns only at GC
   time, into whatever stderr nobody watches).

The rule accepts a spawn whose result is assigned, awaited, passed as an
argument, or immediately chained (`.add_done_callback(...)`).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Finding, Rule, Source, register

_SPAWN_FUNCS = {"ensure_future", "create_task"}


def _local_async_names(tree: ast.Module) -> Set[str]:
    """Names of async defs in this module: bare `foo` and method `bar` for
    `async def bar` inside a class (matched via `self.bar(...)`)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            names.add(node.name)
    return names


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_local_coroutine_call(node: ast.Call, async_names: Set[str]) -> bool:
    """`foo()` or `self.foo()` where foo is an async def in this module.
    Calls through other receivers (`asyncio.run(...)`, `obj.close()`) are
    out of scope: the receiver's type is unknown to a lexical pass."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in async_names
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
    ):
        return func.attr in async_names
    return False


@register
class OrphanTaskRule(Rule):
    name = "no-orphan-task"
    description = (
        "spawned task handle dropped (weakly-held: may be GC'd mid-flight, "
        "exceptions lost) or same-module coroutine called without await "
        "(never runs at all)"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        async_names = _local_async_names(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            name = _call_name(value)
            if name in _SPAWN_FUNCS:
                findings.append(
                    self.finding(
                        src,
                        value,
                        f"{name}(...) handle dropped — the loop holds tasks "
                        "weakly, so this task can be GC'd mid-flight and "
                        "its exception is lost; keep the handle (and detach "
                        "it in a done callback) or await it",
                    )
                )
            elif _is_local_coroutine_call(value, async_names):
                findings.append(
                    self.finding(
                        src,
                        value,
                        f"coroutine {name}(...) is never awaited — the call "
                        "creates a coroutine object and drops it, so the "
                        "body never runs",
                    )
                )
        return findings
