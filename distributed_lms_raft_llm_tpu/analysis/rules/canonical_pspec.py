"""canonical-pspec: one spelling per replicated PartitionSpec.

The PR-2 incident: `P()` and `P(None, None)` describe the SAME replicated
layout, but the pjit cache keys on the spelling — two producers of one
SlotState plane using different spellings made every (S, width) step
program silently recompile on the first live request (tens of seconds of
XLA per width, in production, after warmup claimed to have covered it).
`engine/paged._state_spec` now canonicalizes at the dispatch boundary; this
rule keeps new code from reintroducing the mixed-spelling hazard at the
source: a literal trailing `None` in a `PartitionSpec(...)` / `P(...)`
call is redundant (specs pad with None) and creates a second spelling of
whatever the trailing-None-free form already says. `P(None, None)` is
spelled `P()`, `P("tp", None)` is spelled `P("tp")`, and so on.

Legitimate full-rank spellings (shard_map in_specs documenting every axis
explicitly) carry a suppression with the reason.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Rule, Source, register

_PSPEC_NAMES = {"P", "PartitionSpec"}


def _is_pspec_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in _PSPEC_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr == "PartitionSpec"
    return False


def _canonical(args: List[ast.expr]) -> str:
    kept = list(args)
    while kept and isinstance(kept[-1], ast.Constant) and kept[-1].value is None:
        kept.pop()
    try:
        inner = ", ".join(ast.unparse(a) for a in kept)
    except Exception:  # pragma: no cover - unparse is best-effort detail
        inner = "..."
    return f"P({inner})"


@register
class CanonicalPSpecRule(Rule):
    name = "canonical-pspec"
    description = (
        "PartitionSpec literals must not end in None: trailing Nones are a "
        "second spelling of the same sharding, and spelling-keyed jit "
        "caches silently recompile on the mismatch (the PR-2 bug class)"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not _is_pspec_call(node):
                continue
            # Starred construction (P(*dims)) is a computed spec — the
            # canonicalizers build those on purpose; only literal trailing
            # Nones are a spelling choice someone typed.
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue
            if not node.args:
                continue
            last = node.args[-1]
            if isinstance(last, ast.Constant) and last.value is None:
                findings.append(
                    self.finding(
                        src,
                        node,
                        "non-canonical PartitionSpec spelling (trailing "
                        f"None); write {_canonical(node.args)} so every "
                        "producer of this layout shares one jit-cache key",
                    )
                )
        return findings
