"""program-inventory: the checked-in manifest of jit entry points matches
the tree, and warmup covers it.

`engine/program_inventory.py` is generated from the static jit scan and
cross-validated at runtime by `compile_count_guard(
expected_from_inventory(engine))`. This rule closes the static side of
the loop on every lint run:

- **uninventoried**: a `jax.jit(...)` entry point in the engine modules
  with no matching manifest entry — a new program shipped unclassified
  (no warmup claim, no guard coverage).
- **stale**: a manifest entry no jit site matches — the engine moved on
  and the manifest (plus whatever dashboards/guards trust it) lies.
- **drift**: entry and site agree on identity but disagree on
  `donate_argnums`/`static_argnums` — the donation contract the
  donation-safety rule enforces is keyed off the manifest's claim.
- **warmup-miss**: an entry with `coverage="warmup"` whose owning class
  has a `warmup` method from which no call to that program is reachable
  (call-graph closure, so coverage through helpers like
  `TutoringEngine.warmup -> generate_ids` counts). Deleting one warmup
  step fails here before the runtime guard ever runs.

Matching keys on (engine, attr, target) — line numbers drift with
unrelated edits and are deliberately not part of the manifest.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import absint
from ..core import Finding, register
from ..project import FunctionInfo, Project, ProjectRule

DEFAULT_MANIFEST = "distributed_lms_raft_llm_tpu/engine/program_inventory.py"


class ManifestEntry:
    def __init__(self, line: int, fields: Dict[str, object]):
        self.line = line
        self.engine = str(fields.get("engine", ""))
        self.attr = str(fields.get("attr", ""))
        self.target = str(fields.get("target", ""))
        self.donate_argnums = tuple(fields.get("donate_argnums", ()) or ())
        self.static_argnums = tuple(fields.get("static_argnums", ()) or ())
        self.domain = str(fields.get("domain", ""))
        self.coverage = str(fields.get("coverage", ""))

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.engine, self.attr, self.target)


def _literal(node: ast.expr) -> object:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def parse_manifest(tree: ast.AST) -> List[ManifestEntry]:
    """The ProgramEntry(...) literals of the INVENTORY assignment."""
    entries: List[ManifestEntry] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target]
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "INVENTORY" for t in targets
        ):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        for elt in value.elts:
            if not (
                isinstance(elt, ast.Call)
                and (
                    (isinstance(elt.func, ast.Name)
                     and elt.func.id == "ProgramEntry")
                    or (isinstance(elt.func, ast.Attribute)
                        and elt.func.attr == "ProgramEntry")
                )
            ):
                continue
            fields = {
                kw.arg: _literal(kw.value)
                for kw in elt.keywords if kw.arg is not None
            }
            entries.append(ManifestEntry(elt.lineno, fields))
    return entries


@register
class ProgramInventoryRule(ProjectRule):
    name = "program-inventory"
    description = (
        "the engine's jit entry points and the generated manifest "
        "(engine/program_inventory.py) must match, and every "
        "warmup-covered inventoried program must be reachable from its "
        "engine's warmup() — uncovered programs stall the first live "
        "request with an XLA compile (the PR-2 class)"
    )

    # Absence claims ("no site matches") need the whole tree.
    full_project_only = True

    def __init__(
        self,
        scan_prefixes: Sequence[str] = (absint.ENGINE_PREFIX,),
        manifest_rel: str = DEFAULT_MANIFEST,
    ):
        self.scan_prefixes = tuple(scan_prefixes)
        self.manifest_rel = manifest_rel

    def check_project(self, project: Project) -> List[Finding]:
        manifest_src = project.sources.get(self.manifest_rel)
        findings: List[Finding] = []
        if manifest_src is None:
            # Report on every scanned jit site: the manifest is missing
            # entirely (deleted, or the fixture forgot it).
            for site in self._sites(project):
                findings.append(Finding(
                    rule=self.name, path=site.rel, line=site.line,
                    message=(
                        f"jit entry point `{site.owner or site.rel}."
                        f"{site.attr or site.target}` has no program "
                        f"manifest ({self.manifest_rel} not found); "
                        "generate one (scripts/gen_program_inventory.py)"
                    ),
                ))
            return findings
        entries = parse_manifest(manifest_src.tree)
        sites = self._sites(project)
        by_key: Dict[Tuple[str, str, str], List[ManifestEntry]] = {}
        for e in entries:
            by_key.setdefault(e.key, []).append(e)

        matched: Set[int] = set()
        for site in sites:
            candidates = by_key.get(site.key, [])
            if not candidates:
                label = f"{site.owner}.{site.attr}" if site.owner else (
                    site.attr or site.target
                )
                findings.append(Finding(
                    rule=self.name, path=site.rel, line=site.line,
                    message=(
                        f"uninventoried jit entry point `{label}` (wraps "
                        f"`{site.target}`): every compiled program must be "
                        "classified in engine/program_inventory.py — "
                        "regenerate (scripts/gen_program_inventory.py "
                        "--write) and pick its coverage class"
                    ),
                ))
                continue
            entry = candidates[0]
            matched.add(id(entry))
            if (
                entry.donate_argnums != site.donate_argnums
                or entry.static_argnums != site.static_argnums
            ):
                findings.append(Finding(
                    rule=self.name, path=site.rel, line=site.line,
                    message=(
                        f"inventory drift for `{site.owner}.{site.attr}`: "
                        f"site has donate={site.donate_argnums} "
                        f"static={site.static_argnums}, manifest says "
                        f"donate={entry.donate_argnums} "
                        f"static={entry.static_argnums} — regenerate the "
                        "manifest so the donation contract stays true"
                    ),
                ))
        for entry in entries:
            if id(entry) not in matched:
                findings.append(Finding(
                    rule=self.name, path=self.manifest_rel, line=entry.line,
                    message=(
                        f"stale inventory entry `{entry.engine}."
                        f"{entry.attr}` (wraps `{entry.target}`): no jit "
                        "site in the engine matches — regenerate the "
                        "manifest (scripts/gen_program_inventory.py --write)"
                    ),
                ))

        findings.extend(self._check_warmup_coverage(project, entries, sites))
        return findings

    # ------------------------------------------------------------------

    def _sites(self, project: Project) -> List[absint.JitSite]:
        return [
            s for s in absint.scan_jit_sites(
                project, self.scan_prefixes,
                exclude_rels=(self.manifest_rel,),
            )
            if s.attr  # unbound jit expressions have no program identity
        ]

    def _check_warmup_coverage(
        self, project: Project, entries: List[ManifestEntry],
        sites: List[absint.JitSite],
    ) -> List[Finding]:
        findings: List[Finding] = []
        site_rel = {s.key: s.rel for s in sites}
        covered_classes: Dict[str, Optional[FunctionInfo]] = {}
        seen: Set[Tuple[str, str]] = set()
        for entry in entries:
            if entry.coverage != "warmup" or not entry.engine:
                continue
            if entry.key not in site_rel:
                continue  # already reported as stale
            if (entry.engine, entry.attr) in seen:
                continue  # one finding per program, not per wrapped variant
            seen.add((entry.engine, entry.attr))
            if entry.engine not in covered_classes:
                covered_classes[entry.engine] = self._warmup_fn(
                    project, entry.engine
                )
            warmup = covered_classes[entry.engine]
            if warmup is None:
                findings.append(Finding(
                    rule=self.name, path=site_rel[entry.key], line=1,
                    message=(
                        f"inventory marks `{entry.engine}.{entry.attr}` as "
                        "warmup-covered but the class has no warmup() "
                        "method — add one or reclassify the entry as "
                        "on-demand"
                    ),
                ))
                continue
            if not self._reaches_attr_call(project, warmup, entry.attr):
                findings.append(Finding(
                    rule=self.name, path=warmup.rel, line=warmup.node.lineno,
                    message=(
                        f"warmup no longer covers inventoried program "
                        f"`{entry.engine}.{entry.attr}`: no call to "
                        f"`self.{entry.attr}(...)` is reachable from "
                        "warmup() — the first live request would pay its "
                        "XLA compile (restore the warmup step or "
                        "reclassify the entry)"
                    ),
                ))
        return findings

    @staticmethod
    def _warmup_fn(
        project: Project, engine: str
    ) -> Optional[FunctionInfo]:
        for fn in project.functions.values():
            if fn.class_name == engine and fn.name == "warmup":
                return fn
        return None

    @staticmethod
    def _reaches_attr_call(
        project: Project, warmup: FunctionInfo, attr: str
    ) -> bool:
        reachable = project.reachable([warmup.qname])
        for qname in reachable:
            fn = project.functions.get(qname)
            if fn is None:
                continue
            for node in ast.walk(fn.node):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == attr
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                ):
                    return True
        return False
