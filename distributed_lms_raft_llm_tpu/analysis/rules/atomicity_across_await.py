"""atomicity-across-await: loop-confined state must not be decided
before an await and written after it without re-validation.

Incident class: the event-loop TOCTOU. Single-threaded asyncio code
needs no locks *between* suspension points — every ``await`` is the only
place another task can run. Which means every read-decide-await-write
sequence silently assumes nothing changed across the await:

    if rid not in self._inflight:          # read + decide
        result = await self._fetch(rid)    # suspension — anyone may run
        self._inflight[rid] = result       # write the stale decision

Two tasks hit the same branch, both await, both write: double fetch,
lost update, duplicate side effects. The batcher/pool admission paths
are exactly this shape.

The rule runs per async method over :mod:`analysis.concurrency`'s *true*
suspension model (an await of a project-local coroutine that never
suspends is not a window; ``async for``/``async with`` are), and flags a
write of a shared attribute when:

- some read of the same attribute happens before the latest suspension
  preceding the write, and
- no read of it happens between that suspension and the write
  (a re-read after the await is the re-validation the fix needs).

Reads that are just the base of a store target (``self._cache[k] = v``
reads ``self._cache`` only to store into it) do not count — a blind
store after an await is not a decision. An ``AugAssign`` counts as an
implicit read at the statement start (``self._n += await f()`` is a
lost-update by construction).

Which attributes are "shared": every ``# guarded-by: event-loop``
annotated attribute (the PR-6 convention — loop-confined by contract),
plus a conservative inference fallback for unannotated state: an
attribute initialized in ``__init__`` and mutated in two or more other
methods, at least one of them async, with no other guarded-by
annotation (lock-guarded attrs have their own rule) and that is not
itself a lock.

Remedies: re-read/re-check after the await; restructure so decide and
write sit in one synchronous stretch (decide after the await); or hold
an ``asyncio.Lock`` across the whole sequence. Sanction deliberate
last-wins semantics with ``# lint: disable=atomicity-across-await`` and
a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..concurrency import concurrency_engine
from ..core import Finding, Source, register
from ..project import ClassInfo, Project, ProjectRule
from .guarded_by import EVENT_LOOP, _line_annotation, _self_attr

_Pos = Tuple[int, int]

# In-place mutator method names (mirrors guarded_by's set): a
# `self._q.append(x)` is a write of `self._q` for interleaving purposes.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "put_nowait",
}


def _own_nodes(fn: ast.AST) -> List[ast.AST]:
    """All nodes of `fn`'s body excluding nested function/lambda bodies.

    Nested defs are opaque wherever they appear — as child nodes or as
    statements sitting directly in the body list.
    """
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _is_store_base(node: ast.expr) -> bool:
    """Is this Load just the base of a store target (`self.x[k] = v`)?"""
    cur: ast.AST = node
    parent = getattr(cur, "parent", None)
    while isinstance(parent, (ast.Subscript, ast.Attribute)) \
            and getattr(parent, "value", None) is cur:
        if isinstance(parent.ctx, (ast.Store, ast.Del)):
            return True
        cur = parent
        parent = getattr(cur, "parent", None)
    return False


def _end_pos(node: ast.AST) -> _Pos:
    return (
        getattr(node, "end_lineno", getattr(node, "lineno", 0)) or 0,
        getattr(node, "end_col_offset", 0) or 0,
    )


def _start_pos(node: ast.AST) -> _Pos:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


@register
class AtomicityAcrossAwaitRule(ProjectRule):
    name = "atomicity-across-await"
    description = (
        "shared event-loop state read before a suspension point and "
        "written after it without re-validation — the event-loop TOCTOU"
    )

    def check_project(self, project: Project) -> List[Finding]:
        engine = concurrency_engine(project)
        # _own_nodes is needed once per method in _shared_attrs and again
        # in _check_method; memoize per function node for the run.
        self._own_cache: Dict[int, List[ast.AST]] = {}
        findings: List[Finding] = []
        for class_key in sorted(project.classes):
            cls = project.classes[class_key]
            src = project.sources.get(cls.rel)
            if src is None:
                continue
            shared = self._shared_attrs(src, cls)
            if not shared:
                continue
            facts = engine._class_facts.get(class_key)
            lock_attrs = (
                set(facts.lock_attrs) if facts is not None else set()
            )
            for name, method in sorted(cls.methods.items()):
                if not method.is_async:
                    continue
                susp = [
                    ((s.line, s.col), s) for s in
                    engine.true_suspensions(method.qname)
                ]
                if not susp:
                    continue
                findings.extend(self._check_method(
                    src, cls, method.node, shared, lock_attrs, susp
                ))
        return findings

    def _own(self, fn: ast.AST) -> List[ast.AST]:
        cached = self._own_cache.get(id(fn))
        if cached is None:
            cached = _own_nodes(fn)
            self._own_cache[id(fn)] = cached
        return cached

    # ----------------------------------------------------- shared attrs

    def _shared_attrs(
        self, src: Source, cls: ClassInfo
    ) -> Dict[str, str]:
        """attr -> basis ("annotated" | "inferred")."""
        annotated: Set[str] = set()
        other_guard: Set[str] = set()
        init_attrs: Set[str] = set()
        writers: Dict[str, Set[str]] = {}
        async_writers: Dict[str, Set[str]] = {}
        for method in cls.methods.values():
            is_init = method.name == "__init__"
            is_async = method.is_async
            for node in self._own(method.node):
                for attr in self._written_attrs(node):
                    if is_init:
                        init_attrs.add(attr)
                        guard = _line_annotation(src, node.lineno)
                        if guard == EVENT_LOOP:
                            annotated.add(attr)
                        elif guard is not None:
                            other_guard.add(attr)
                    else:
                        writers.setdefault(attr, set()).add(method.name)
                        if is_async:
                            async_writers.setdefault(attr, set()).add(
                                method.name
                            )
        # Annotations may also sit on non-__init__ declarations.
        for node in ast.walk(cls.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                guard = _line_annotation(src, node.lineno)
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if guard == EVENT_LOOP:
                        annotated.add(attr)
                    elif guard is not None:
                        other_guard.add(attr)
        out: Dict[str, str] = {}
        for attr in annotated:
            out[attr] = "annotated"
        for attr, methods in writers.items():
            if attr in out or attr in other_guard:
                continue
            if attr not in init_attrs:
                continue
            if len(methods) >= 2 and async_writers.get(attr):
                out[attr] = "inferred"
        return out

    @staticmethod
    def _written_attrs(node: ast.AST) -> List[str]:
        out: List[str] = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.append(attr)
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        out.append(attr)
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                attr = _self_attr(func.value)
                if attr is not None:
                    out.append(attr)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        out.append(attr)
        return out

    # --------------------------------------------------------- the check

    def _check_method(
        self,
        src: Source,
        cls: ClassInfo,
        fn: ast.AST,
        shared: Dict[str, str],
        lock_attrs: Set[str],
        susp: List[Tuple[_Pos, object]],
    ) -> List[Finding]:
        reads: List[Tuple[str, _Pos]] = []
        writes: List[Tuple[str, _Pos, int, str]] = []  # attr, end, line, kind
        for node in self._own(fn):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                attr = _self_attr(node)
                if attr in shared and attr not in lock_attrs \
                        and not _is_store_base(node) \
                        and not self._is_mutator_base(node):
                    reads.append((str(attr), _start_pos(node)))
            for attr in self._written_attrs(node):
                if attr not in shared or attr in lock_attrs:
                    continue
                kind = (
                    "augmented assignment"
                    if isinstance(node, ast.AugAssign) else
                    "mutation" if isinstance(node, ast.Call) else
                    "assignment"
                )
                writes.append(
                    (attr, _end_pos(node), node.lineno, kind)
                )
                if isinstance(node, ast.AugAssign):
                    # The old value is read at the statement start.
                    reads.append((attr, _start_pos(node)))
        findings: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        positions = sorted(p for p, _ in susp)
        details = {p: s for p, s in susp}
        for attr, wpos, wline, kind in writes:
            before = [p for p in positions if p < wpos]
            if not before:
                continue
            s = max(before)
            pre = [p for a, p in reads if a == attr and p <= s]
            if not pre:
                continue
            if any(s < p < wpos for a, p in reads if a == attr):
                continue  # re-validated after the await
            key = (wline, attr)
            if key in seen:
                continue
            seen.add(key)
            susp_obj = details[s]
            basis = shared[attr]
            basis_note = (
                "declared `# guarded-by: event-loop`" if basis == "annotated"
                else "inferred shared (initialized in __init__, mutated "
                     "from multiple methods)"
            )
            findings.append(self.finding(
                src, wline,
                f"{cls.name}: self.{attr} is read (line {max(pre)[0]}) "
                f"before a suspension point (line "
                f"{getattr(susp_obj, 'line', s[0])}, "
                f"{getattr(susp_obj, 'detail', 'await')}) and this "
                f"{kind} happens after it without re-reading — other "
                "tasks run across the await, so the decision may be "
                f"stale ({basis_note}); re-validate self.{attr} after "
                "the await, restructure decide+write into one "
                "synchronous stretch, or hold an asyncio.Lock across "
                "the sequence",
            ))
        return findings

    @staticmethod
    def _is_mutator_base(node: ast.expr) -> bool:
        """`self._q` inside `self._q.append(x)` — counted as the write,
        not as a decision read."""
        parent = getattr(node, "parent", None)
        if isinstance(parent, ast.Attribute) and parent.value is node \
                and parent.attr in _MUTATORS:
            grand = getattr(parent, "parent", None)
            return isinstance(grand, ast.Call) and grand.func is parent
        return False
