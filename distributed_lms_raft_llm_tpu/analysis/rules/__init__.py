"""The rule catalog. Importing this package registers every rule.

Each module holds one rule targeting one of this codebase's demonstrated
bug classes (see the module docstrings for the incident each rule encodes).
Per-file lexical rules came with PR 3; the semantic rules (deadline-flow,
metrics-registry, config-consistency, guarded-by-flow) run on the
whole-repo symbol table + call graph in analysis/project.py; the
abstract-interpretation rules (pspec-flow, donation-safety, dtype-flow,
program-inventory) additionally propagate values — sharding meaning,
dtype, donation status, compiled-program domains — via analysis/absint.py;
the effect/taint rules (state-machine-determinism, wire-taint) run on the
interprocedural effect lattice in analysis/effects.py; the concurrency
rules (atomicity-across-await, lock-order, await-under-lock, and the
per-file cancellation-safety) run on the suspension-point + lockset
model in analysis/concurrency.py.
"""

from . import (  # noqa: F401
    async_blocking,
    atomicity_across_await,
    await_under_lock,
    cancellation_safety,
    canonical_pspec,
    config_consistency,
    deadline_flow,
    donation_safety,
    dtype_flow,
    durable_rename,
    guarded_by,
    guarded_by_flow,
    host_sync,
    lock_order,
    metrics_registry,
    orphan_task,
    program_inventory,
    pspec_flow,
    slow_marker,
    state_machine_determinism,
    trace_propagation,
    tracer_hygiene,
    wire_taint,
)
