"""The rule catalog. Importing this package registers every rule.

Each module holds one rule targeting one of this codebase's demonstrated
bug classes (see the module docstrings for the incident each rule encodes).
"""

from . import (  # noqa: F401
    async_blocking,
    canonical_pspec,
    guarded_by,
    host_sync,
    orphan_task,
    slow_marker,
    tracer_hygiene,
)
