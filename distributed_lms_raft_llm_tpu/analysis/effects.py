"""Interprocedural effect inference over the Project call graph.

PR 4's model answers "who calls whom"; this module answers "what does a
call *do*" — specifically, which replica-visible side channels a function
can touch. Every function gets a set drawn from a small effect lattice:

- ``READS_CLOCK``    — ``time.time()``, ``datetime.now()``, ...
- ``READS_RNG``      — ``random.*``, ``uuid.*``, ``os.urandom``, ...
- ``READS_ENV``      — ``os.environ`` / ``os.getenv``
- ``PROCESS_LOCAL``  — ``os.getpid()``, ``id()``, thread identity
- ``UNORDERED_ITER`` — iterating a set without ``sorted()`` where the
  loop body writes (hash randomization makes the visit order differ
  across replica processes, so any insertion-ordered output diverges)
- ``IO``             — filesystem access (``open``, ``os.remove``, ...)
- ``RPC_EGRESS``     — awaited gRPC stub calls (CamelCase-attr calls,
  the repo-wide stub idiom) or anything under ``grpc.*``
- ``BLOCKING``       — ``time.sleep``, ``subprocess.*``

Leaf effects are recognized *only* when ``Project.resolve_call`` cannot
resolve the callee to a project-local function — a module that defines
its own ``open`` or ``id`` shadows the intrinsic, matching Python's own
name resolution. Effects then close transitively over a spawn-aware copy
of the call graph:

- calls handed to spawn wrappers (``asyncio.ensure_future``,
  ``create_task``, ``run_in_executor``, ``loop.call_soon``, ...) are NOT
  walked into — the work runs off the caller's synchronous path, which
  is exactly the distinction the determinism rule needs (the LMS applier
  *spawns* blob replication; it must never *await* it);
- the ``getattr(self, f"_apply_{...}")`` dispatch idiom is resolved by
  naming convention: a method whose body builds such an accessor gets
  edges to every ``_apply_*``-prefixed method of its class.

The closure is a fixpoint over the (small) graph and each Source is
parsed at most once via the shared cache in ``analysis.core``, so a warm
``run_lint()`` pays one linear pass — the wall-budget test in
``tests/test_lint_clean.py`` keeps that honest.

Like the Project model, the engine is unsound-by-design: unresolved
dynamic dispatch contributes no edge, so rules built on it lose findings
rather than invent them (see ``analysis/project.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import weakref
from typing import Dict, FrozenSet, Iterable, List, MutableMapping, Optional, Sequence, Set, Tuple

from .project import FunctionInfo, Project, _dotted

__all__ = [
    "READS_CLOCK",
    "READS_RNG",
    "READS_ENV",
    "PROCESS_LOCAL",
    "UNORDERED_ITER",
    "IO",
    "RPC_EGRESS",
    "BLOCKING",
    "NONDETERMINISM_EFFECTS",
    "EffectSite",
    "Witness",
    "EffectEngine",
    "effect_engine",
]

READS_CLOCK = "reads-clock"
READS_RNG = "reads-rng"
READS_ENV = "reads-env"
PROCESS_LOCAL = "process-local"
UNORDERED_ITER = "unordered-iter"
IO = "io"
RPC_EGRESS = "rpc-egress"
BLOCKING = "blocking"

#: Everything that can make two replicas applying the same command differ,
#: plus the on-tick-loop hazards (egress/blocking). The determinism rule
#: forbids the whole set on applier paths.
NONDETERMINISM_EFFECTS: FrozenSet[str] = frozenset({
    READS_CLOCK, READS_RNG, READS_ENV, PROCESS_LOCAL, UNORDERED_ITER,
    IO, RPC_EGRESS, BLOCKING,
})

# ------------------------------------------------------------ intrinsics

_CLOCK_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today", "date.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}
_RNG_PREFIXES = ("random.", "secrets.", "uuid.")
_RNG_DOTTED = {"os.urandom", "os.getrandom"}
_RNG_BARE = {"uuid4", "uuid1", "urandom", "token_hex", "token_bytes"}
_ENV_DOTTED = {"os.getenv", "os.environ.get", "os.environ"}
_PROCESS_DOTTED = {
    "os.getpid", "os.getppid",
    "threading.get_ident", "threading.current_thread",
}
_BLOCKING_DOTTED = {"time.sleep"}
_BLOCKING_PREFIXES = ("subprocess.",)
_IO_BARE = {"open"}
_IO_PREFIXES = ("shutil.", "tempfile.")
_IO_DOTTED = {
    "os.remove", "os.unlink", "os.replace", "os.rename", "os.makedirs",
    "os.mkdir", "os.rmdir", "os.listdir", "os.scandir", "os.stat",
    "os.fsync", "os.open", "os.write", "os.read",
    "os.path.exists", "os.path.getsize",
}
_RPC_PREFIXES = ("grpc.",)

#: Call names (last dotted component) whose ARGUMENTS run off the
#: caller's synchronous path. The scanner does not descend into them.
_SPAWN_WRAPPERS = {
    "ensure_future", "create_task", "add_done_callback",
    "call_soon", "call_soon_threadsafe", "call_later",
    "run_in_executor", "to_thread", "Thread",
}

#: Loop-body operations that count as "the iteration order escaped into
#: replicated state" for UNORDERED_ITER.
_MUTATOR_ATTRS = {
    "append", "add", "insert", "update", "pop", "setdefault",
    "extend", "remove", "discard",
}


@dataclasses.dataclass(frozen=True)
class EffectSite:
    """One leaf occurrence of an effect inside a single function."""

    rel: str
    line: int
    effect: str
    detail: str    # human-readable leaf, e.g. "time.time()" or "for over set"


@dataclasses.dataclass(frozen=True)
class Witness:
    """A call chain from a rule root down to the leaf effect site."""

    chain: Tuple[str, ...]   # qnames, root first
    site: EffectSite

    def pretty(self) -> str:
        names = [q.split("::", 1)[-1] for q in self.chain]
        return " -> ".join(names + [self.site.detail])


def _last(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _classify_call(node: ast.Call, *, awaited: bool) -> Optional[Tuple[str, str]]:
    """(effect, detail) for an *unresolved* call, else None."""
    dotted = _dotted(node.func)
    if dotted:
        tail2 = ".".join(dotted.split(".")[-2:])
        if dotted in _CLOCK_DOTTED or tail2 in _CLOCK_DOTTED:
            return (READS_CLOCK, f"{dotted}()")
        if dotted in _RNG_DOTTED or dotted.startswith(_RNG_PREFIXES) \
                or _last(dotted) in _RNG_BARE:
            return (READS_RNG, f"{dotted}()")
        if dotted in _ENV_DOTTED:
            return (READS_ENV, f"{dotted}()")
        if dotted in _PROCESS_DOTTED:
            return (PROCESS_LOCAL, f"{dotted}()")
        if dotted in _BLOCKING_DOTTED or dotted.startswith(_BLOCKING_PREFIXES):
            return (BLOCKING, f"{dotted}()")
        if dotted in _IO_DOTTED or dotted.startswith(_IO_PREFIXES) \
                or dotted in _IO_BARE:
            return (IO, f"{dotted}()")
        if dotted.startswith(_RPC_PREFIXES):
            return (RPC_EGRESS, f"{dotted}()")
        if dotted == "id" and len(node.args) == 1:
            return (PROCESS_LOCAL, "id()")
    # gRPC stub idiom: an awaited CamelCase-attribute call, or one carrying
    # a timeout= kwarg (matches the trace-propagation rule's heuristic).
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr[:1].isupper() and (
            awaited or any(k.arg == "timeout" for k in node.keywords)
        ):
            return (RPC_EGRESS, f".{attr}(...)")
    return None


def _is_setlike(node: ast.expr, setlike_names: Set[str]) -> bool:
    """Does this expression evaluate to hash-ordered contents?"""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Name):
        return node.id in setlike_names
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
        # list(set(x)) / tuple(set(x)) freeze the hash order, they do
        # not impose one; sorted(set(x)) does and is therefore clean.
        if node.func.id in ("list", "tuple") and node.args:
            return _is_setlike(node.args[0], setlike_names)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_setlike(node.left, setlike_names) or _is_setlike(
            node.right, setlike_names
        )
    return False


def _body_writes(body: Sequence[ast.stmt]) -> bool:
    """Does a loop body write somewhere the iteration order can escape?"""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        return True
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATOR_ATTRS:
                return True
    return False


class _FunctionScan:
    """Spawn-aware single pass over one function body: leaf effect sites,
    resolved call edges, and convention-dispatch prefixes."""

    def __init__(self, project: Project, fn: FunctionInfo):
        self.project = project
        self.fn = fn
        self.mod = project.modules[fn.rel]
        self.sites: List[EffectSite] = []
        self.edges: Set[str] = set()
        self.dispatch_prefixes: Set[str] = set()
        self._setlike: Set[str] = set()
        self._seen: Set[Tuple[int, str]] = set()
        body = getattr(fn.node, "body", [])
        for stmt in body:
            self._scan(stmt)

    def _add_site(self, line: int, effect: str, detail: str) -> None:
        key = (line, effect)
        if key in self._seen:
            return
        self._seen.add(key)
        self.sites.append(EffectSite(self.fn.rel, line, effect, detail))

    def _scan(self, node: ast.AST, *, awaited: bool = False) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs own their bodies; the parent->nested edge is
            # added by the engine (defining implies it may run).
            return
        if isinstance(node, ast.Await):
            self._scan(node.value, awaited=True)
            return
        if isinstance(node, ast.Call):
            self._scan_call(node, awaited=awaited)
            return
        if isinstance(node, (ast.Name, ast.Attribute)):
            if _dotted(node) == "os.environ":
                self._add_site(node.lineno, READS_ENV, "os.environ")
            if isinstance(node, ast.Attribute):
                self._scan(node.value)
            return
        if isinstance(node, ast.Assign):
            self._scan(node.value)
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                if _is_setlike(node.value, self._setlike):
                    self._setlike.add(node.targets[0].id)
                else:
                    self._setlike.discard(node.targets[0].id)
            for t in node.targets:
                self._scan(t)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._scan_for(node)
            return
        if isinstance(node, (ast.ListComp, ast.DictComp, ast.GeneratorExp)):
            self._scan_comp(node)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child)

    def _scan_call(self, node: ast.Call, *, awaited: bool) -> None:
        dotted = _dotted(node.func)
        if dotted and _last(dotted) in _SPAWN_WRAPPERS:
            # The arguments run off this function's synchronous path:
            # record nothing and do not descend.
            return
        self._detect_dispatch(node)
        callee = self.project.resolve_call(
            self.mod, node.func, self.fn.class_name, self.fn
        )
        if callee is not None:
            self.edges.add(callee.qname)
        else:
            hit = _classify_call(node, awaited=awaited)
            if hit is not None:
                self._add_site(node.lineno, hit[0], hit[1])
        for child in ast.iter_child_nodes(node):
            if child is node.func and isinstance(child, ast.Attribute):
                self._scan(child.value)
                continue
            if child is node.func:
                continue
            self._scan(child)

    def _detect_dispatch(self, node: ast.Call) -> None:
        """`getattr(self, f"_apply_{op}")` -> dispatch prefix "_apply_"."""
        if not (isinstance(node.func, ast.Name) and node.func.id == "getattr"):
            return
        if len(node.args) < 2:
            return
        if not (isinstance(node.args[0], ast.Name)
                and node.args[0].id == "self"):
            return
        key = node.args[1]
        if isinstance(key, ast.JoinedStr) and key.values:
            first = key.values[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str) and first.value:
                self.dispatch_prefixes.add(first.value)

    def _scan_for(self, node: ast.AST) -> None:
        it = node.iter  # type: ignore[attr-defined]
        body = node.body  # type: ignore[attr-defined]
        orelse = node.orelse  # type: ignore[attr-defined]
        if _is_setlike(it, self._setlike) and _body_writes(body):
            self._add_site(
                node.lineno,  # type: ignore[attr-defined]
                UNORDERED_ITER,
                "for over set (hash order)",
            )
        self._scan(it)
        for stmt in list(body) + list(orelse):
            self._scan(stmt)

    def _scan_comp(self, node: ast.expr) -> None:
        # A list/dict comprehension over a set freezes hash order into an
        # ordered container — unless it feeds straight into sorted().
        parent = getattr(node, "parent", None)
        in_sorted = (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Name)
            and parent.func.id == "sorted"
        )
        gens = node.generators  # type: ignore[attr-defined]
        if not in_sorted and not isinstance(node, ast.GeneratorExp):
            for gen in gens:
                if _is_setlike(gen.iter, self._setlike):
                    self._add_site(
                        node.lineno, UNORDERED_ITER,
                        "comprehension over set (hash order)",
                    )
                    break
        for child in ast.iter_child_nodes(node):
            self._scan(child)


class EffectEngine:
    """Per-function effect sets closed over a spawn-aware call graph."""

    def __init__(self, project: Project):
        self.project = project
        self._sites: Dict[str, List[EffectSite]] = {}
        self._edges: Dict[str, Set[str]] = {}
        self._effects: Dict[str, Set[str]] = {}
        self._build()
        self._close()

    # ------------------------------------------------------------- build

    def _build(self) -> None:
        for qname, fn in self.project.functions.items():
            scan = _FunctionScan(self.project, fn)
            edges = set(scan.edges)
            if fn.parent is not None:
                self._edges.setdefault(fn.parent, set()).add(qname)
            for prefix in scan.dispatch_prefixes:
                edges |= self._convention_targets(fn, prefix)
            self._sites[qname] = scan.sites
            self._edges.setdefault(qname, set()).update(edges)

    def _convention_targets(self, fn: FunctionInfo, prefix: str) -> Set[str]:
        if fn.class_name is None:
            return set()
        cls = self.project.classes.get(f"{fn.rel}::{fn.class_name}")
        if cls is None:
            return set()
        return {
            m.qname for name, m in cls.methods.items()
            if name.startswith(prefix)
        }

    def _close(self) -> None:
        for qname in self.project.functions:
            self._effects[qname] = {s.effect for s in self._sites.get(qname, ())}
        changed = True
        while changed:
            changed = False
            for qname in self.project.functions:
                eff = self._effects[qname]
                before = len(eff)
                for callee in self._edges.get(qname, ()):
                    callee_eff = self._effects.get(callee)
                    if callee_eff:
                        eff |= callee_eff
                if len(eff) != before:
                    changed = True

    # ----------------------------------------------------------- queries

    def effects(self, qname: str) -> FrozenSet[str]:
        return frozenset(self._effects.get(qname, ()))

    def local_sites(self, qname: str) -> List[EffectSite]:
        return list(self._sites.get(qname, ()))

    def callees(self, qname: str) -> Set[str]:
        return set(self._edges.get(qname, ()))

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.project.functions]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, set()) - seen)
        return seen

    def witness(self, root: str, effect: str) -> Optional[Witness]:
        """Shortest call chain from `root` to a local site of `effect`
        (BFS, neighbors in sorted order, so the chain is deterministic)."""
        if effect not in self.effects(root):
            return None
        parent: Dict[str, Optional[str]] = {root: None}
        queue: List[str] = [root]
        while queue:
            cur = queue.pop(0)
            for site in self._sites.get(cur, ()):
                if site.effect == effect:
                    chain: List[str] = []
                    walk: Optional[str] = cur
                    while walk is not None:
                        chain.append(walk)
                        walk = parent[walk]
                    return Witness(tuple(reversed(chain)), site)
            for nxt in sorted(self._edges.get(cur, ())):
                if nxt not in parent and effect in self.effects(nxt):
                    parent[nxt] = cur
                    queue.append(nxt)
        return None


# One engine per Project instance: both effect rules (and any future one)
# share the build. Weak keys keep test-constructed throwaway Projects
# collectable.
_ENGINES: MutableMapping[Project, EffectEngine] = weakref.WeakKeyDictionary()


def effect_engine(project: Project) -> EffectEngine:
    engine = _ENGINES.get(project)
    if engine is None:
        engine = EffectEngine(project)
        _ENGINES[project] = engine
    return engine
