"""Interprocedural concurrency model: suspension points + locksets.

PR 4's project model answers "who calls whom", PR 18's effect engine
answers "what does a call do"; this module answers the two questions the
interleaving-bug class needs:

- **where can this function suspend?** Every ``await``, ``async with``
  and ``async for`` is a *suspension point* — except that awaiting a
  project-local coroutine which itself never suspends does NOT yield to
  the event loop (CPython runs it to completion synchronously), so the
  model resolves awaited project calls through the call graph and closes
  ``may_suspend`` to a fixpoint. Rules built on it can therefore tell a
  real interleaving window from an await that is structurally atomic.

- **which locks can this call path hold/acquire?** ``threading.Lock`` /
  ``threading.RLock`` / ``asyncio.Lock`` creations are collected into a
  lock table keyed by declaration site (``<rel>::Class.attr`` — one key
  per *declaration*, so two instances of the same class share a key,
  which is exactly the granularity that catches PR 13's two-breaker
  self-deadlock). Acquisitions via ``with`` / ``async with`` /
  ``.acquire()`` are tracked with the held-set at each event, closed
  transitively over call edges, and every cross-lock acquisition becomes
  an edge in a global lock-acquisition **order graph** with witness
  chains like ``effects.py``.

To resolve attribute-chain calls (``node.breaker.state_code()``) the
engine layers a deliberately small type inference over the project
model: class attribute types from ``self.x = Ctor()`` / parameter
annotations, parameter types from annotations (``Optional[...]``
unwrapped), and local variable types from constructor assignments.
``@property`` loads whose receiver type is known contribute call edges
too — a property that takes a lock (``CircuitBreaker.state``) is a call
in every sense that matters here.

Callback linkage (the PR-13 shape): calls through unresolvable callables
(a parameter, a ``self._cb`` field) made while holding a lock are
recorded as *dynamic call sites*; functions/lambdas passed to
``set_*_callback`` / ``add_*_callback``-style registrars (or
``callback=`` / ``on_*=`` keywords) are recorded as *registered
callbacks*. A registered callback whose transitive lockset intersects a
dynamic site's held locks is the single-thread self-deadlock that froze
the serving loop in PR 13. Callback-derived edges also enter the order
graph (tagged), so the runtime ``utils/locks.py`` graph can be checked
for consistency against the static one.

Entry-held convention: a method whose ``def`` line carries
``# guarded-by: <attr>`` (the PR-6 annotation, attr naming a lock of the
same class) is analyzed with that lock in its entry held-set — callers
hold it, so suspensions/acquisitions inside are events under the lock.

Like the project/effect models this is unsound-by-design: unresolved
dynamic dispatch contributes no edge and lexical position stands in for
program order, so rules lose findings rather than invent them — except
the callback linkage above, which is deliberately conservative (any
registered callback may run at any dynamic site) because that is the
direction the deadlock class demands.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import weakref
from typing import (
    Dict,
    FrozenSet,
    Iterable,
    List,
    MutableMapping,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from .effects import _classify_call, BLOCKING, _SPAWN_WRAPPERS
from .project import FunctionInfo, ModuleInfo, Project, _dotted

__all__ = [
    "KIND_THREADING",
    "KIND_ASYNCIO",
    "LockInfo",
    "Suspension",
    "Acquisition",
    "CallEvent",
    "BlockingEvent",
    "DynamicCall",
    "OrderEdge",
    "LockWitness",
    "ConcurrencyEngine",
    "concurrency_engine",
]

KIND_THREADING = "threading"
KIND_ASYNCIO = "asyncio"

# Same annotation grammar as rules/guarded_by.py (kept local: rule
# modules import this engine, so the engine cannot import the rules
# package without a cycle).
_ANNOT_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w\-]*)")

# Constructor spellings -> (kind, reentrant). `make_lock`/`OrderedLock`
# are the utils/locks.py runtime counterpart: debug wrappers around
# threading locks, so they inherit threading semantics.
_LOCK_CTORS: Dict[str, Tuple[str, bool]] = {
    "threading.Lock": (KIND_THREADING, False),
    "threading.RLock": (KIND_THREADING, True),
    "asyncio.Lock": (KIND_ASYNCIO, False),
    "make_lock": (KIND_THREADING, False),
    "locks.make_lock": (KIND_THREADING, False),
    "OrderedLock": (KIND_THREADING, False),
    "locks.OrderedLock": (KIND_THREADING, False),
}
_BARE_LOCK_IMPORTS = {
    ("threading", "Lock"): (KIND_THREADING, False),
    ("threading", "RLock"): (KIND_THREADING, True),
    ("asyncio", "Lock"): (KIND_ASYNCIO, False),
}

# Call names that register a callable to be invoked later by the callee
# (`set_state_change_callback`, `add_done_listener`, ...) and keyword
# names that carry one.
_REGISTRAR_RE = re.compile(
    r"^(set|add|register|on)_.*(callback|listener|hook)s?$"
)
_CALLBACK_KWARG_RE = re.compile(r"(^on_)|callback|_cb$|^cb$|_hook$")

_PROPERTY_DECOS = {"property", "cached_property", "functools.cached_property"}


@dataclasses.dataclass(frozen=True)
class LockInfo:
    """One lock *declaration* (all instances share the key)."""

    key: str        # "<rel>::Class.attr" or "<rel>::name"
    short: str      # "Class.attr" or "name" — the runtime-visible name
    kind: str       # KIND_THREADING | KIND_ASYNCIO
    reentrant: bool
    rel: str
    line: int


@dataclasses.dataclass(frozen=True)
class Suspension:
    """One potential yield-to-event-loop point inside a function.

    ``callee`` is set when the suspension is an awaited project-local
    call: it only actually suspends when the callee's ``may_suspend``
    closes to True (`ConcurrencyEngine.true_suspensions` applies that)."""

    rel: str
    line: int
    col: int
    detail: str
    held: FrozenSet[str]
    callee: Optional[str]


@dataclasses.dataclass(frozen=True)
class Acquisition:
    lock: str
    rel: str
    line: int
    held: FrozenSet[str]   # held BEFORE this acquisition
    via: str               # "with" | "async with" | "acquire()"


@dataclasses.dataclass(frozen=True)
class CallEvent:
    callee: str
    rel: str
    line: int
    held: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class BlockingEvent:
    """An unresolved blocking intrinsic (PR-18 lattice) at a call site."""

    rel: str
    line: int
    detail: str
    held: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class DynamicCall:
    """A call through an unresolvable callable while holding locks."""

    rel: str
    line: int
    detail: str
    held: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class OrderEdge:
    """`dst` acquired (possibly transitively) while `src` is held."""

    src: str
    dst: str
    qname: str   # function containing the event that created the edge
    rel: str
    line: int
    via: str     # "with"/"acquire()" | "call" | "callback"


@dataclasses.dataclass(frozen=True)
class LockWitness:
    """Call chain from a root function down to the acquisition site."""

    chain: Tuple[str, ...]
    site: Acquisition

    def pretty(self, short: str) -> str:
        names = [q.split("::", 1)[-1] for q in self.chain]
        return " -> ".join(names + [f"acquire {short}"])


def _line_annotation(src_lines: Sequence[str], lineno: int) -> Optional[str]:
    if 1 <= lineno <= len(src_lines):
        m = _ANNOT_RE.search(src_lines[lineno - 1])
        if m:
            return m.group(1)
    if lineno >= 2:
        above = src_lines[lineno - 2].strip()
        if above.startswith("#"):
            m = _ANNOT_RE.search(above)
            if m:
                return m.group(1)
    return None


def _last(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassFacts:
    """Per-class lock declarations, attribute types, and lock guards."""

    def __init__(self) -> None:
        self.lock_attrs: Dict[str, str] = {}    # attr -> lock key
        self.attr_types: Dict[str, str] = {}    # attr -> class key


class ConcurrencyEngine:
    """Suspension model + interprocedural lockset analysis."""

    def __init__(self, project: Project):
        self.project = project
        self.locks: Dict[str, LockInfo] = {}
        self._class_facts: Dict[str, _ClassFacts] = {}
        self._module_locks: Dict[str, Dict[str, str]] = {}
        self._bare_lock_names: Dict[str, Dict[str, Tuple[str, bool]]] = {}
        self._suspensions: Dict[str, List[Suspension]] = {}
        self._acquisitions: Dict[str, List[Acquisition]] = {}
        self._calls: Dict[str, List[CallEvent]] = {}
        self._blocking: Dict[str, List[BlockingEvent]] = {}
        self._dynamic: Dict[str, List[DynamicCall]] = {}
        self._entry_held: Dict[str, FrozenSet[str]] = {}
        self._may_suspend: Dict[str, bool] = {}
        self._locksets: Dict[str, Set[str]] = {}
        self._registered: Dict[str, Tuple[str, int]] = {}  # qname -> site
        self._collect_locks_and_types()
        self._scan_functions()
        self._close_may_suspend()
        self._close_locksets()
        self._edges = self._build_order_edges()

    # -------------------------------------------------- pass 1: lock table

    def _bare_locks(self, mod: ModuleInfo) -> Dict[str, Tuple[str, bool]]:
        cached = self._bare_lock_names.get(mod.rel)
        if cached is not None:
            return cached
        out: Dict[str, Tuple[str, bool]] = {}
        for node in ast.walk(mod.src.tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                "threading", "asyncio",
            ):
                for alias in node.names:
                    hit = _BARE_LOCK_IMPORTS.get((node.module, alias.name))
                    if hit is not None:
                        out[alias.asname or alias.name] = hit
        self._bare_lock_names[mod.rel] = out
        return out

    def _lock_ctor(
        self, mod: ModuleInfo, expr: ast.expr
    ) -> Optional[Tuple[str, bool]]:
        """(kind, reentrant) when `expr` constructs a lock, else None."""
        if isinstance(expr, ast.BoolOp):
            for value in expr.values:
                hit = self._lock_ctor(mod, value)
                if hit is not None:
                    return hit
            return None
        if not isinstance(expr, ast.Call):
            return None
        dotted = _dotted(expr.func)
        hit = _LOCK_CTORS.get(dotted)
        if hit is None and isinstance(expr.func, ast.Name):
            hit = self._bare_locks(mod).get(expr.func.id)
        if hit is None:
            return None
        kind, reentrant = hit
        for kw in expr.keywords:
            if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
                reentrant = bool(kw.value.value)
        return (kind, reentrant)

    def _resolve_class_key(
        self, mod: ModuleInfo, name: str
    ) -> Optional[str]:
        if name in mod.classes:
            return f"{mod.rel}::{name}"
        imp = mod.imports.get(name)
        if imp is not None and imp[0] == "sym":
            key = f"{imp[1]}::{imp[2]}"
            if key in self.project.classes:
                return key
        return None

    def _ann_class(
        self, mod: ModuleInfo, ann: Optional[ast.expr]
    ) -> Optional[str]:
        """Class key an annotation denotes; Optional[...] unwrapped."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip()
            if name.isidentifier():
                return self._resolve_class_key(mod, name)
            return None
        if isinstance(ann, ast.Name):
            return self._resolve_class_key(mod, ann.id)
        if isinstance(ann, ast.Attribute):
            dotted = _dotted(ann)
            head, _, tail = dotted.partition(".")
            imp = mod.imports.get(head)
            if imp is not None and imp[0] == "mod" and "." not in tail:
                key = f"{imp[1]}::{tail}"
                if key in self.project.classes:
                    return key
            return None
        if isinstance(ann, ast.Subscript):
            base = _last(_dotted(ann.value))
            if base == "Optional":
                return self._ann_class(mod, ann.slice)
            if base == "Union" and isinstance(ann.slice, ast.Tuple):
                for elt in ann.slice.elts:
                    hit = self._ann_class(mod, elt)
                    if hit is not None:
                        return hit
        return None

    def _ctor_class(
        self, mod: ModuleInfo, expr: ast.expr
    ) -> Optional[str]:
        """Class key when `expr` constructs a project-local class."""
        if isinstance(expr, ast.BoolOp):
            for value in expr.values:
                hit = self._ctor_class(mod, value)
                if hit is not None:
                    return hit
            return None
        if not isinstance(expr, ast.Call):
            return None
        func = expr.func
        if isinstance(func, ast.Name):
            return self._resolve_class_key(mod, func.id)
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            imp = mod.imports.get(func.value.id)
            if imp is not None and imp[0] == "mod":
                key = f"{imp[1]}::{func.attr}"
                if key in self.project.classes:
                    return key
        return None

    def _param_types(self, fn: FunctionInfo) -> Dict[str, str]:
        mod = self.project.modules[fn.rel]
        node = fn.node
        out: Dict[str, str] = {}
        args = getattr(node, "args", None)
        if args is None:
            return out
        for arg in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            key = self._ann_class(mod, arg.annotation)
            if key is not None:
                out[arg.arg] = key
        return out

    def _collect_locks_and_types(self) -> None:
        for class_key, cls in self.project.classes.items():
            facts = _ClassFacts()
            self._class_facts[class_key] = facts
            mod = self.project.modules[cls.rel]
            for method in cls.methods.values():
                params = self._param_types(method)
                for node in ast.walk(method.node):
                    if isinstance(node, ast.AnnAssign):
                        attr = _self_attr(node.target)
                        if attr is None:
                            continue
                        hit = self._ann_class(mod, node.annotation)
                        if hit is not None:
                            facts.attr_types.setdefault(attr, hit)
                        if node.value is not None:
                            self._note_attr_assign(
                                mod, cls.name, facts, attr,
                                node.value, node.lineno, params,
                            )
                    elif isinstance(node, ast.Assign):
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                self._note_attr_assign(
                                    mod, cls.name, facts, attr,
                                    node.value, node.lineno, params,
                                )
        for rel, mod in self.project.modules.items():
            table: Dict[str, str] = {}
            for stmt in mod.src.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                hit = self._lock_ctor(mod, stmt.value)
                if hit is None:
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        key = f"{rel}::{t.id}"
                        table[t.id] = key
                        self.locks.setdefault(key, LockInfo(
                            key=key, short=t.id, kind=hit[0],
                            reentrant=hit[1], rel=rel, line=stmt.lineno,
                        ))
            self._module_locks[rel] = table

    def _note_attr_assign(
        self,
        mod: ModuleInfo,
        class_name: str,
        facts: _ClassFacts,
        attr: str,
        value: ast.expr,
        lineno: int,
        params: Dict[str, str],
    ) -> None:
        lock = self._lock_ctor(mod, value)
        if lock is not None:
            key = f"{mod.rel}::{class_name}.{attr}"
            facts.lock_attrs.setdefault(attr, key)
            self.locks.setdefault(key, LockInfo(
                key=key, short=f"{class_name}.{attr}", kind=lock[0],
                reentrant=lock[1], rel=mod.rel, line=lineno,
            ))
            return
        hit = self._ctor_class(mod, value)
        if hit is None and isinstance(value, ast.Name):
            hit = params.get(value.id)
        if hit is None and isinstance(value, ast.BoolOp):
            for v in value.values:
                if isinstance(v, ast.Name) and v.id in params:
                    hit = params[v.id]
                    break
        if hit is not None:
            facts.attr_types.setdefault(attr, hit)

    # ------------------------------------------------- pass 2: function scan

    def _class_key_of(self, fn: FunctionInfo) -> Optional[str]:
        if fn.class_name is None:
            return None
        key = f"{fn.rel}::{fn.class_name}"
        return key if key in self.project.classes else None

    def _scan_functions(self) -> None:
        for qname, fn in self.project.functions.items():
            scan = _FnScan(self, fn)
            self._suspensions[qname] = scan.suspensions
            self._acquisitions[qname] = scan.acquisitions
            self._calls[qname] = scan.calls
            self._blocking[qname] = scan.blocking
            self._dynamic[qname] = scan.dynamic_calls
            self._entry_held[qname] = scan.entry_held
            for cb, site in scan.registered.items():
                self._registered.setdefault(cb, site)

    # ----------------------------------------------------- pass 3: closures

    def _close_may_suspend(self) -> None:
        for qname, fn in self.project.functions.items():
            self._may_suspend[qname] = fn.is_async and any(
                s.callee is None for s in self._suspensions[qname]
            )
        changed = True
        while changed:
            changed = False
            for qname, fn in self.project.functions.items():
                if self._may_suspend[qname] or not fn.is_async:
                    continue
                for s in self._suspensions[qname]:
                    if s.callee is not None and self._may_suspend.get(
                        s.callee, False
                    ):
                        self._may_suspend[qname] = True
                        changed = True
                        break

    def _close_locksets(self) -> None:
        for qname in self.project.functions:
            self._locksets[qname] = {
                a.lock for a in self._acquisitions[qname]
            }
        changed = True
        while changed:
            changed = False
            for qname in self.project.functions:
                mine = self._locksets[qname]
                before = len(mine)
                for call in self._calls[qname]:
                    callee = self._locksets.get(call.callee)
                    if callee:
                        mine |= callee
                if len(mine) != before:
                    changed = True

    # ------------------------------------------------ pass 4: order graph

    def _build_order_edges(self) -> Dict[Tuple[str, str], OrderEdge]:
        edges: Dict[Tuple[str, str], OrderEdge] = {}

        def add(src: str, dst: str, qname: str, rel: str, line: int,
                via: str) -> None:
            if src == dst:
                return
            edges.setdefault((src, dst), OrderEdge(
                src=src, dst=dst, qname=qname, rel=rel, line=line, via=via,
            ))

        for qname in self.project.functions:
            for acq in self._acquisitions[qname]:
                for held in acq.held:
                    add(held, acq.lock, qname, acq.rel, acq.line, acq.via)
            for call in self._calls[qname]:
                if not call.held:
                    continue
                for dst in self._locksets.get(call.callee, ()):
                    if dst in call.held:
                        continue
                    for src in call.held:
                        add(src, dst, qname, call.rel, call.line, "call")
            for dyn in self._dynamic[qname]:
                for cb in self._registered:
                    for dst in self._locksets.get(cb, ()):
                        if dst in dyn.held:
                            continue
                        for src in dyn.held:
                            add(src, dst, qname, dyn.rel, dyn.line,
                                "callback")
        return edges

    # ----------------------------------------------------------- queries

    def suspensions(self, qname: str) -> List[Suspension]:
        return list(self._suspensions.get(qname, ()))

    def may_suspend(self, qname: str) -> bool:
        return self._may_suspend.get(qname, False)

    def true_suspensions(self, qname: str) -> List[Suspension]:
        """Suspension events that can actually yield to the event loop."""
        return [
            s for s in self._suspensions.get(qname, ())
            if s.callee is None or self._may_suspend.get(s.callee, False)
        ]

    def acquisitions(self, qname: str) -> List[Acquisition]:
        return list(self._acquisitions.get(qname, ()))

    def calls(self, qname: str) -> List[CallEvent]:
        return list(self._calls.get(qname, ()))

    def blocking_events(self, qname: str) -> List[BlockingEvent]:
        return list(self._blocking.get(qname, ()))

    def dynamic_calls(self, qname: str) -> List[DynamicCall]:
        return list(self._dynamic.get(qname, ()))

    def entry_held(self, qname: str) -> FrozenSet[str]:
        return self._entry_held.get(qname, frozenset())

    def lockset(self, qname: str) -> FrozenSet[str]:
        """Locks `qname` may acquire, transitively over call edges."""
        return frozenset(self._locksets.get(qname, ()))

    def registered_callbacks(self) -> Dict[str, Tuple[str, int]]:
        """qname -> (rel, line) of one registration site."""
        return dict(self._registered)

    def order_edges(self) -> Dict[Tuple[str, str], OrderEdge]:
        return dict(self._edges)

    def static_order_shorts(self) -> Set[Tuple[str, str]]:
        """Order edges on runtime-visible lock names, for cross-validation
        against the live graph `utils/locks.py` records in debug mode."""
        out: Set[Tuple[str, str]] = set()
        for (src, dst) in self._edges:
            a, b = self.locks.get(src), self.locks.get(dst)
            if a is not None and b is not None:
                out.add((a.short, b.short))
        return out

    def held_threading(self, held: Iterable[str]) -> List[str]:
        return sorted(
            k for k in held
            if self.locks.get(k) is not None
            and self.locks[k].kind == KIND_THREADING
        )

    def short(self, key: str) -> str:
        info = self.locks.get(key)
        return info.short if info is not None else key

    def lock_witness(
        self, root: str, lock: str
    ) -> Optional[LockWitness]:
        """Shortest call chain from `root` to an acquisition of `lock`
        (BFS, sorted neighbors — deterministic like effects.witness)."""
        if lock not in self._locksets.get(root, ()):
            return None
        parent: Dict[str, Optional[str]] = {root: None}
        queue: List[str] = [root]
        while queue:
            cur = queue.pop(0)
            for acq in self._acquisitions.get(cur, ()):
                if acq.lock == lock:
                    chain: List[str] = []
                    walk: Optional[str] = cur
                    while walk is not None:
                        chain.append(walk)
                        walk = parent[walk]
                    return LockWitness(tuple(reversed(chain)), acq)
            for call in sorted(
                self._calls.get(cur, ()), key=lambda c: c.callee
            ):
                nxt = call.callee
                if nxt not in parent and lock in self._locksets.get(nxt, ()):
                    parent[nxt] = cur
                    queue.append(nxt)
        return None

    def cycles(self) -> List[List[str]]:
        """Strongly connected components of the order graph with >= 2
        locks — each is a potential deadlock cycle. Deterministic order."""
        adj: Dict[str, List[str]] = {}
        for (src, dst) in self._edges:
            adj.setdefault(src, []).append(dst)
            adj.setdefault(dst, [])
        for outs in adj.values():
            outs.sort()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str) -> None:
            # Iterative Tarjan: (node, iterator-position) frames.
            work: List[Tuple[str, int]] = [(v, 0)]
            while work:
                node, pos = work.pop()
                if pos == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                outs = adj.get(node, [])
                for i in range(pos, len(outs)):
                    w = outs[i]
                    if w not in index:
                        work.append((node, i + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) >= 2:
                        sccs.append(sorted(comp))
                if work:
                    parent_node = work[-1][0]
                    low[parent_node] = min(low[parent_node], low[node])

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return sorted(sccs)


class _FnScan:
    """One ordered pass over a function body: suspension points,
    acquisitions (with held-sets), resolved/typed call events, dynamic
    call sites, blocking intrinsics, and callback registrations."""

    def __init__(self, engine: ConcurrencyEngine, fn: FunctionInfo):
        self.engine = engine
        self.fn = fn
        self.mod = engine.project.modules[fn.rel]
        self.class_key = engine._class_key_of(fn)
        self.params = engine._param_types(fn)
        self.local_types: Dict[str, str] = {}
        self.local_names: Set[str] = set(self.params)
        self.suspensions: List[Suspension] = []
        self.acquisitions: List[Acquisition] = []
        self.calls: List[CallEvent] = []
        self.blocking: List[BlockingEvent] = []
        self.dynamic_calls: List[DynamicCall] = []
        self.registered: Dict[str, Tuple[str, int]] = {}
        args = getattr(fn.node, "args", None)
        if args is not None:
            for arg in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
            ):
                self.local_names.add(arg.arg)
        self.entry_held = self._entry_held()
        self._held: Set[str] = set(self.entry_held)
        self._seen_calls: Set[Tuple[int, str]] = set()
        for stmt in getattr(fn.node, "body", []):
            self._stmt(stmt)

    # ------------------------------------------------------------ helpers

    def _entry_held(self) -> FrozenSet[str]:
        """`# guarded-by: <lock-attr>` on the def line = callers hold it."""
        guard = _line_annotation(self.fn.src.lines, self.fn.node.lineno)
        if guard is None or self.class_key is None:
            return frozenset()
        facts = self.engine._class_facts.get(self.class_key)
        if facts is None:
            return frozenset()
        key = facts.lock_attrs.get(guard)
        return frozenset((key,)) if key is not None else frozenset()

    def _expr_class(self, expr: ast.expr) -> Optional[str]:
        """Class key an expression's value has, when inference can see it."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return self.class_key
            return self.local_types.get(expr.id) or self.params.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_class(expr.value)
            if base is None:
                return None
            facts = self.engine._class_facts.get(base)
            if facts is None:
                return None
            return facts.attr_types.get(expr.attr)
        if isinstance(expr, ast.Call):
            return self.engine._ctor_class(self.mod, expr)
        return None

    def _lock_key(self, expr: ast.expr) -> Optional[str]:
        """Lock-table key an expression denotes, else None."""
        if isinstance(expr, ast.Name):
            return self.engine._module_locks.get(self.mod.rel, {}).get(
                expr.id
            )
        if isinstance(expr, ast.Attribute):
            base = self._expr_class(expr.value)
            if base is not None:
                facts = self.engine._class_facts.get(base)
                if facts is not None:
                    key = facts.lock_attrs.get(expr.attr)
                    if key is not None:
                        return key
            if isinstance(expr.value, ast.Name) and expr.value.id != "self":
                # module-level lock referenced through an import alias
                imp = self.mod.imports.get(expr.value.id)
                if imp is not None and imp[0] == "mod":
                    return self.engine._module_locks.get(imp[1], {}).get(
                        expr.attr
                    )
        return None

    def _resolve(self, call: ast.Call) -> Optional[FunctionInfo]:
        """Project heuristic resolution, then the typed-chain fallback."""
        callee = self.engine.project.resolve_call(
            self.mod, call.func, self.fn.class_name, self.fn
        )
        if callee is not None:
            return callee
        func = call.func
        if isinstance(func, ast.Attribute):
            base = self._expr_class(func.value)
            if base is not None:
                cls = self.engine.project.classes[base]
                owner = self.engine.project.modules[cls.rel]
                return self.engine.project._lookup_method(
                    owner, cls.name, func.attr
                )
        return None

    def _property_target(
        self, node: ast.Attribute
    ) -> Optional[FunctionInfo]:
        base = self._expr_class(node.value)
        if base is None:
            return None
        cls = self.engine.project.classes[base]
        owner = self.engine.project.modules[cls.rel]
        target = self.engine.project._lookup_method(
            owner, cls.name, node.attr
        )
        if target is None:
            return None
        for deco in getattr(target.node, "decorator_list", []):
            if _dotted(deco) in _PROPERTY_DECOS:
                return target
        return None

    def _held_snapshot(self) -> FrozenSet[str]:
        return frozenset(self._held)

    def _suspend(
        self, node: ast.AST, detail: str, callee: Optional[str] = None
    ) -> None:
        self.suspensions.append(Suspension(
            rel=self.fn.rel,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            detail=detail,
            held=self._held_snapshot(),
            callee=callee,
        ))

    def _acquire(self, key: str, node: ast.AST, via: str) -> None:
        self.acquisitions.append(Acquisition(
            lock=key, rel=self.fn.rel,
            line=getattr(node, "lineno", 0),
            held=self._held_snapshot(), via=via,
        ))

    # --------------------------------------------------------- statements

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs own their bodies
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node)
            return
        if isinstance(node, ast.AsyncFor):
            self._suspend(node, "async for")
            self._expr(node.iter)
            for stmt in list(node.body) + list(node.orelse):
                self._stmt(stmt)
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value)
            if len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                name = node.targets[0].id
                self.local_names.add(name)
                hit = self._expr_class(node.value)
                if hit is not None:
                    self.local_types[name] = hit
                else:
                    self.local_types.pop(name, None)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    self._expr(t)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._expr(node.value)
            if isinstance(node.target, ast.Name):
                name = node.target.id
                self.local_names.add(name)
                hit = self.engine._ann_class(self.mod, node.annotation)
                if hit is not None:
                    self.local_types[name] = hit
            return
        # Generic statement: walk expressions in order, recurse into
        # nested statement lists so held-set mutations stay sequential.
        for field in ast.iter_child_nodes(node):
            if isinstance(field, ast.stmt):
                self._stmt(field)
            elif isinstance(field, ast.expr):
                self._expr(field)
            elif isinstance(field, ast.ExceptHandler):
                for stmt in field.body:
                    self._stmt(stmt)
            elif isinstance(field, (ast.arguments, ast.keyword)):
                self._expr_children(field)

    def _with(self, node: ast.stmt) -> None:
        is_async = isinstance(node, ast.AsyncWith)
        items = node.items  # type: ignore[attr-defined]
        added: List[str] = []
        for item in items:
            expr = item.context_expr
            key = self._lock_key(expr)
            if is_async:
                # Entering any async context manager can suspend; for an
                # asyncio lock the suspension is the acquire itself.
                detail = (
                    f"async with {self.engine.short(key)}" if key
                    else "async with"
                )
                self._suspend(item.context_expr, detail)
            if key is not None:
                self._acquire(
                    key, expr, "async with" if is_async else "with"
                )
                if key not in self._held:
                    self._held.add(key)
                    added.append(key)
            else:
                self._expr(expr)
        for stmt in node.body:  # type: ignore[attr-defined]
            self._stmt(stmt)
        for key in added:
            self._held.discard(key)

    # -------------------------------------------------------- expressions

    def _expr_children(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _expr(self, node: ast.expr) -> None:
        if isinstance(node, (ast.Lambda,)):
            return  # runs later; registrations are caught at the call site
        if isinstance(node, ast.Await):
            self._await(node)
            return
        if isinstance(node, ast.Call):
            self._call(node, awaited=False)
            return
        if isinstance(node, ast.Attribute):
            prop = self._property_target(node)
            if prop is not None:
                self._record_call(prop, node)
            self._expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.comprehension):
                self._expr(child.iter)
                for cond in child.ifs:
                    self._expr(cond)

    def _await(self, node: ast.Await) -> None:
        value = node.value
        if isinstance(value, ast.Call):
            callee = self._call(value, awaited=True)
            if callee is not None and callee.is_async:
                self._suspend(
                    node, f"await {callee.name}()", callee=callee.qname
                )
            else:
                self._suspend(
                    node, f"await {_dotted(value.func) or '<call>'}()"
                )
        else:
            self._expr(value)
            self._suspend(node, f"await {_dotted(value) or '<expr>'}")

    def _call(
        self, node: ast.Call, *, awaited: bool
    ) -> Optional[FunctionInfo]:
        dotted = _dotted(node.func)
        if dotted and _last(dotted) in _SPAWN_WRAPPERS:
            # Arguments run off this synchronous path (spawn-aware, like
            # effects.py). Spawned callables are NOT treated as registered
            # callbacks either: they run on their own thread/task, never
            # synchronously inside a locked dynamic call site, so pairing
            # them with held locks would only manufacture false cycles.
            return None
        self._note_registrations(node)
        # .acquire()/.release() on a known lock mutate the held set for
        # the REST of the function (or until released).
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "acquire", "release",
        ):
            key = self._lock_key(node.func.value)
            if key is not None:
                if node.func.attr == "acquire":
                    if awaited:
                        self._suspend(
                            node, f"await {self.engine.short(key)}.acquire()"
                        )
                    self._acquire(key, node, "acquire()")
                    self._held.add(key)
                else:
                    self._held.discard(key)
                for arg in node.args:
                    self._expr(arg)
                return None
        callee = self._resolve(node)
        if callee is not None:
            self._record_call(callee, node)
        else:
            hit = _classify_call(node, awaited=awaited)
            if hit is not None and hit[0] == BLOCKING:
                self.blocking.append(BlockingEvent(
                    rel=self.fn.rel, line=node.lineno, detail=hit[1],
                    held=self._held_snapshot(),
                ))
            elif self._held and self._is_dynamic_callable(node.func):
                self.dynamic_calls.append(DynamicCall(
                    rel=self.fn.rel, line=node.lineno,
                    detail=f"{_dotted(node.func) or '<callable>'}(...)",
                    held=self._held_snapshot(),
                ))
        for child in ast.iter_child_nodes(node):
            if child is node.func:
                if isinstance(child, ast.Attribute):
                    self._expr(child.value)
                continue
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.keyword):
                self._expr(child.value)
        return callee

    def _record_call(self, callee: FunctionInfo, node: ast.AST) -> None:
        key = (getattr(node, "lineno", 0), callee.qname)
        if key in self._seen_calls:
            return
        self._seen_calls.add(key)
        self.calls.append(CallEvent(
            callee=callee.qname, rel=self.fn.rel,
            line=getattr(node, "lineno", 0), held=self._held_snapshot(),
        ))

    def _is_dynamic_callable(self, func: ast.expr) -> bool:
        """A callable the graph cannot see through: a parameter/local
        variable, or a self-attribute that is not a method (a stored
        callback field). Module aliases (`log.warning`) are excluded —
        they are ordinary library calls, not injected callables."""
        if isinstance(func, ast.Name):
            return func.id in self.local_names
        attr = _self_attr(func)
        if attr is not None and self.class_key is not None:
            cls = self.engine.project.classes[self.class_key]
            owner = self.engine.project.modules[cls.rel]
            method = self.engine.project._lookup_method(
                owner, cls.name, attr
            )
            facts = self.engine._class_facts.get(self.class_key)
            typed = facts is not None and attr in facts.attr_types
            return method is None and not typed
        return False

    def _note_registrations(self, node: ast.Call) -> None:
        """Collect callables handed to registrar-style calls:
        `set_state_change_callback(lambda: ...)`, `callback=self._on_x`,
        `on_change=handler`."""
        func_name = ""
        if isinstance(node.func, ast.Attribute):
            func_name = node.func.attr
        elif isinstance(node.func, ast.Name):
            func_name = node.func.id
        is_registrar = bool(_REGISTRAR_RE.match(func_name))
        for arg in node.args:
            if is_registrar:
                self._register_callable(arg, node.lineno)
        for kw in node.keywords:
            if is_registrar or (
                kw.arg is not None and _CALLBACK_KWARG_RE.search(kw.arg)
            ):
                self._register_callable(kw.value, node.lineno)

    def _register_callable(self, expr: ast.expr, lineno: int) -> None:
        if isinstance(expr, ast.Lambda):
            body = expr.body
            if isinstance(body, ast.Call):
                callee = self._resolve(body)
                if callee is not None:
                    self.registered.setdefault(
                        callee.qname, (self.fn.rel, lineno)
                    )
            return
        if isinstance(expr, (ast.Name, ast.Attribute)):
            target = self.engine.project.resolve_call(
                self.mod, expr, self.fn.class_name, self.fn
            )
            if target is None and isinstance(expr, ast.Attribute):
                base = self._expr_class(expr.value)
                if base is not None:
                    cls = self.engine.project.classes[base]
                    owner = self.engine.project.modules[cls.rel]
                    target = self.engine.project._lookup_method(
                        owner, cls.name, expr.attr
                    )
            if target is not None:
                self.registered.setdefault(
                    target.qname, (self.fn.rel, lineno)
                )


# One engine per Project instance, shared by all four concurrency rules
# (same lifecycle discipline as effects.effect_engine).
_ENGINES: MutableMapping[Project, ConcurrencyEngine] = (
    weakref.WeakKeyDictionary()
)


def concurrency_engine(project: Project) -> ConcurrencyEngine:
    engine = _ENGINES.get(project)
    if engine is None:
        engine = ConcurrencyEngine(project)
        _ENGINES[project] = engine
    return engine
