"""dlrl-absint: abstract interpretation over the engine's jit-reachable code.

PR 4's project model answers *reachability* questions (who can call whom);
the engine bug classes that remain — spelling-consistent but
semantically-divergent shardings, use-after-donate, silent dtype
promotion, warmup that no longer covers the compiled-program set — are
questions about *values*. This module adds the value half: a small
abstract interpreter over the AST that propagates abstract facts
(PartitionSpec meaning, dtype, donation status) through the functions the
jit entry points reach, reusing `analysis/project.py`'s symbol table and
call graph for the interprocedural steps.

Everything here is still pure AST — nothing imports jax or the engine —
so it shares the project model's trade: **missing resolution loses
findings, never invents them.** An expression the evaluator cannot see
through becomes UNKNOWN and contributes nothing; the rules built on top
(pspec-flow, donation-safety, dtype-flow, program-inventory) only report
on facts that were positively derived.

Pieces, each consumed by one or more rules in `analysis/rules/`:

- `scan_jit_sites`: every `jax.jit(...)` call in a module set, with its
  bound attribute (`self._step = jax.jit(...)`), the wrapped program
  function (resolved through `functools.partial`), and literal
  `donate_argnums` / `static_argnums` — the static mirror of the runtime
  program caches that `utils/guards.compile_count_guard` counts.
- `SpecEval` + `collect_plane_puts` + `collect_plane_tables`: evaluates
  PartitionSpec expressions to a canonical *meaning* (trailing Nones
  dropped, helper functions like `paged._plane_spec` resolved through
  their returns, call-site argument binding for nested helpers such as
  `_canon_state.put`, literal plane-name strings flowed into spec-table
  subscripts like `partition.PAGED_PLANE_SPECS[name]`), and collects
  every `jax.device_put` of a named state plane with the spec it lands
  under plus every module-level literal plane->spec table.
- `DtypeWalker`: forward dtype propagation through a function body
  (constructors, `.astype`, project-local calls, arithmetic promotion),
  with hooks that fire on int8->float upcasts and weak-type promotions.
- statement-order utilities (`stmt_chain`, `execution_order`,
  `assigned_chains`, `chain_str`): branch-aware "does this read happen
  after that dispatch" queries for the donation-safety rule.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .project import FunctionInfo, ModuleInfo, Project, _dotted

ENGINE_PREFIX = "distributed_lms_raft_llm_tpu/engine/"


class _Unknown:
    """Bottom of every abstract domain: no fact derived, no finding."""

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<unknown>"


UNKNOWN = _Unknown()

_MAX_DEPTH = 8  # interprocedural evaluation depth bound (cycles included)


# --------------------------------------------------------------- utilities


def chain_str(node: ast.expr) -> Optional[str]:
    """'self.state.active' for pure Name/Attribute chains, else None."""
    out = _dotted(node)
    return out or None


def enclosing_function(src_parents: Iterable[ast.AST]) -> Optional[ast.AST]:
    for anc in src_parents:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class_name(src_parents: Iterable[ast.AST]) -> Optional[str]:
    for anc in src_parents:
        if isinstance(anc, ast.ClassDef):
            return anc.name
    return None


def function_infos_by_node(project: Project, rel: str) -> Dict[int, FunctionInfo]:
    return {
        id(fn.node): fn
        for fn in project.functions.values()
        if fn.rel == rel
    }


_BLOCK_FIELDS = ("body", "orelse", "finalbody", "handlers")


def stmt_chain(node: ast.AST, stop: ast.AST) -> List[Tuple[int, str, int]]:
    """The enclosing-statement path of `node` up to (not including) `stop`,
    outermost first: [(id(owner), block_field, index), ...]. Two nodes'
    chains decide execution order (see `execution_order`)."""
    chain: List[Tuple[int, str, int]] = []
    cur: Optional[ast.AST] = node
    while cur is not None and cur is not stop:
        par = getattr(cur, "parent", None)
        if par is None:
            break
        for field in _BLOCK_FIELDS:
            seq = getattr(par, field, None)
            if isinstance(seq, list):
                for i, item in enumerate(seq):
                    if item is cur:
                        chain.append((id(par), field, i))
                        break
                else:
                    continue
                break
        cur = par
    chain.reverse()
    return chain


def execution_order(
    a: Sequence[Tuple[int, str, int]], b: Sequence[Tuple[int, str, int]]
) -> Optional[bool]:
    """True when chain `a` executes strictly before chain `b` on every path,
    False when strictly after, None when unordered (sibling branches of one
    `if`/`try`, or the same statement)."""
    for ea, eb in zip(a, b):
        if ea == eb:
            continue
        oa, fa, ia = ea
        ob, fb, ib = eb
        if oa == ob and fa == fb:
            return ia < ib
        # Same owner, different block (if-body vs orelse, try vs handler):
        # the two only run on different paths — unordered.
        return None
    return None  # one contains the other / same statement


def assigned_chains(stmt: ast.AST) -> Set[str]:
    """Dotted chains a statement (re)binds: Assign/AugAssign/AnnAssign
    targets, for-targets, with-as names; tuple targets flattened."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets.extend(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets.append(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets.append(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets.extend(
            item.optional_vars for item in stmt.items
            if item.optional_vars is not None
        )
    out: Set[str] = set()
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            chain = chain_str(t)
            if chain:
                out.add(chain)
    return out


# ------------------------------------------------------------ jit entry scan


@dataclasses.dataclass(frozen=True)
class JitSite:
    """One `jax.jit(...)` call: where it is, what it wraps, how it binds."""

    rel: str
    line: int
    owner: str                      # enclosing class name; "" at module level
    attr: str                       # bound name ("_step"); "" if unbound
    is_self_attr: bool              # bound via `self.<attr> = jax.jit(...)`
    target: str                     # wrapped function as written ("bert.embed")
    target_qname: Optional[str]     # resolved project qname, when visible
    donate_argnums: Tuple[int, ...]
    static_argnums: Tuple[int, ...]

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.owner, self.attr, self.target)


def _is_jit_func(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute):
        return (
            func.attr == "jit"
            and isinstance(func.value, ast.Name)
            and func.value.id == "jax"
        )
    return isinstance(func, ast.Name) and func.id == "jit"


def _argnums(call: ast.Call, name: str) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg != name:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


def _unwrap_partial(expr: ast.expr) -> ast.expr:
    """partial(fn, ...) / functools.partial(fn, ...) -> fn; factories
    (`make_step(...)`) unwrap to the factory reference."""
    if isinstance(expr, ast.Call):
        func = expr.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else ""
        )
        if name == "partial" and expr.args:
            return _unwrap_partial(expr.args[0])
        return func
    return expr


def scan_jit_sites(
    project: Project, prefixes: Sequence[str] = (ENGINE_PREFIX,),
    *, exclude_rels: Sequence[str] = (),
) -> List[JitSite]:
    sites: List[JitSite] = []
    for rel, mod in sorted(project.modules.items()):
        if not any(rel.startswith(p) for p in prefixes):
            continue
        if rel in exclude_rels:
            continue
        infos = function_infos_by_node(project, rel)
        for node in ast.walk(mod.src.tree):
            if not isinstance(node, ast.Call) or not _is_jit_func(node.func):
                continue
            if not node.args:
                continue
            target_expr = _unwrap_partial(node.args[0])
            target = _dotted(target_expr) or "<expr>"
            owner = enclosing_class_name(mod.src.parents(node)) or ""
            fn_node = enclosing_function(mod.src.parents(node))
            enclosing = infos.get(id(fn_node)) if fn_node is not None else None
            qname: Optional[str] = None
            if isinstance(target_expr, (ast.Name, ast.Attribute)):
                resolved = project.resolve_call(
                    mod, target_expr,
                    enclosing.class_name if enclosing else None, enclosing,
                )
                qname = resolved.qname if resolved is not None else None
            attr, is_self = "", False
            parent = getattr(node, "parent", None)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                t = parent.targets[0]
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attr, is_self = t.attr, True
                elif isinstance(t, ast.Name):
                    attr = t.id
            sites.append(JitSite(
                rel=rel, line=node.lineno, owner=owner, attr=attr,
                is_self_attr=is_self, target=target, target_qname=qname,
                donate_argnums=_argnums(node, "donate_argnums"),
                static_argnums=_argnums(node, "static_argnums"),
            ))
    return sites


# --------------------------------------------------- PartitionSpec meaning


def canonical_pspec(call: ast.Call) -> object:
    """The canonical MEANING of a literal P(...)/PartitionSpec(...) call:
    trailing Nones dropped, remaining args unparsed. `P()`, `P(None)` and
    `P(None, None)` all evaluate to "P()" — the semantic identity the
    spelling-level `canonical-pspec` rule cannot see."""
    if any(isinstance(a, ast.Starred) for a in call.args) or call.keywords:
        return UNKNOWN
    kept = list(call.args)
    while kept and isinstance(kept[-1], ast.Constant) and kept[-1].value is None:
        kept.pop()
    try:
        inner = ", ".join(ast.unparse(a) for a in kept)
    except Exception:  # pragma: no cover - unparse is best-effort detail
        return UNKNOWN
    return f"P({inner})"


def _is_pspec_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in ("P", "PartitionSpec")
    return isinstance(func, ast.Attribute) and func.attr == "PartitionSpec"


def _is_named_sharding_call(expr: ast.expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    func = expr.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else ""
    )
    return name == "NamedSharding"


def _trailing_name(expr: ast.expr) -> Optional[str]:
    """'PAGED_PLANE_SPECS' from either the bare Name or a module-qualified
    `partition.PAGED_PLANE_SPECS` attribute access."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def collect_plane_tables(project: Project) -> Dict[str, Dict[str, object]]:
    """Every module-level literal spec table in the project: an (optionally
    annotated) assignment of a Name to a dict whose keys are ALL string
    constants and whose values are ALL literal P(...)/PartitionSpec(...)
    calls, each evaluated to its canonical meaning. A dict failing either
    shape test is not a spec table and is skipped whole — partial tables
    would let a half-literal dict masquerade as policy. Keyed by the bare
    table name (`PAGED_PLANE_SPECS`), which is how producer modules
    subscript it whether imported bare or module-qualified."""
    tables: Dict[str, Dict[str, object]] = {}
    for rel, mod in sorted(project.modules.items()):
        for node in mod.src.tree.body:
            if isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            else:
                continue
            if not (isinstance(target, ast.Name)
                    and isinstance(value, ast.Dict) and value.keys):
                continue
            entries: Dict[str, object] = {}
            for k, v in zip(value.keys, value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Call) and _is_pspec_call(v)
                ):
                    entries = {}
                    break
                spec = canonical_pspec(v)
                if isinstance(spec, _Unknown):
                    entries = {}
                    break
                entries[k.value] = spec
            if entries:
                tables[target.id] = entries
    return tables


def plane_tables(project: Project) -> Dict[str, Dict[str, object]]:
    """Memoized collect_plane_tables — SpecEval consults it per Subscript
    and the pspec-flow rule per project, so scan the module set once."""
    cached = getattr(project, "_plane_table_cache", None)
    if cached is None:
        cached = collect_plane_tables(project)
        try:
            project._plane_table_cache = cached
        except Exception:  # pragma: no cover - frozen project models
            pass
    return cached


@dataclasses.dataclass
class Frame:
    """One evaluation scope: explicit bindings (call-site arguments) over
    lazily-resolved local assignments of `fn_node`."""

    bindings: Dict[str, object]
    fn_node: Optional[ast.AST]
    parent: Optional["Frame"] = None


class SpecEval:
    """Evaluate a PartitionSpec-valued expression to its canonical meaning
    (a "P(...)" string), known-None, or UNKNOWN."""

    def __init__(self, project: Project, mod: ModuleInfo):
        self.project = project
        self.mod = mod
        self.infos = function_infos_by_node(project, mod.rel)

    def eval(self, expr: ast.expr, frame: Frame, depth: int = 0) -> object:
        if depth > _MAX_DEPTH:
            return UNKNOWN
        if isinstance(expr, ast.Constant):
            # Strings flow too: plane NAMES key the spec table
            # (`partition.PAGED_PLANE_SPECS[name]`), so a literal plane
            # name bound at a put call site must survive to the Subscript
            # evaluation below. Everything else non-None stays UNKNOWN.
            if expr.value is None:
                return None
            return expr.value if isinstance(expr.value, str) else UNKNOWN
        if isinstance(expr, ast.Name):
            return self._eval_name(expr.id, frame, depth)
        if isinstance(expr, ast.Subscript):
            # `TABLE[name]` against a literal plane-spec table: when the
            # key evaluates to a known string and the subscripted name
            # resolves to a collected table (see collect_plane_tables),
            # the entry's canonical spec IS the value. Anything else —
            # unknown key, unknown table, missing entry — is UNKNOWN
            # (missing resolution loses findings, never invents them).
            key = self.eval(expr.slice, frame, depth + 1)
            if isinstance(key, str):
                tname = _trailing_name(expr.value)
                if tname is not None:
                    table = plane_tables(self.project).get(tname)
                    if table is not None:
                        return table.get(key, UNKNOWN)
            return UNKNOWN
        if isinstance(expr, ast.IfExp):
            test = self._eval_test(expr.test, frame, depth)
            if test is True:
                return self.eval(expr.body, frame, depth + 1)
            if test is False:
                return self.eval(expr.orelse, frame, depth + 1)
            a = self.eval(expr.body, frame, depth + 1)
            b = self.eval(expr.orelse, frame, depth + 1)
            return a if a == b and not isinstance(a, _Unknown) else UNKNOWN
        if isinstance(expr, ast.Call):
            if _is_pspec_call(expr):
                return canonical_pspec(expr)
            if _is_named_sharding_call(expr):
                if len(expr.args) >= 2:
                    return self.eval(expr.args[1], frame, depth + 1)
                return UNKNOWN
            return self._eval_project_call(expr, frame, depth)
        return UNKNOWN

    # Helpers ------------------------------------------------------------

    def _eval_name(self, name: str, frame: Frame, depth: int) -> object:
        cur: Optional[Frame] = frame
        while cur is not None:
            if name in cur.bindings:
                return cur.bindings[name]
            if cur.fn_node is not None:
                assign = self._single_assignment(cur.fn_node, name)
                if assign is not None:
                    return self.eval(assign, cur, depth + 1)
            cur = cur.parent
        return UNKNOWN

    @staticmethod
    def _single_assignment(fn_node: ast.AST, name: str) -> Optional[ast.expr]:
        found: List[ast.expr] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        found.append(node.value)
        return found[0] if len(found) == 1 else None

    def _eval_test(self, test: ast.expr, frame: Frame, depth: int) -> object:
        """Decide `x is None` / `x is not None` when x's value is known."""
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            return UNKNOWN
        left = self.eval(test.left, frame, depth + 1)
        if isinstance(left, _Unknown):
            return UNKNOWN
        is_none = left is None
        return is_none if isinstance(test.ops[0], ast.Is) else not is_none

    def _eval_project_call(
        self, call: ast.Call, frame: Frame, depth: int
    ) -> object:
        fn_node = enclosing_function(self.mod.src.parents(call))
        enclosing = self.infos.get(id(fn_node)) if fn_node is not None else None
        resolved = self.project.resolve_call(
            self.mod, call.func,
            enclosing.class_name if enclosing else None, enclosing,
        )
        if resolved is None:
            return UNKNOWN
        bindings = bind_call_args(resolved.node, call)
        if bindings is None:
            return UNKNOWN
        callee_frame = Frame(
            bindings={
                k: (self.eval(v, frame, depth + 1)
                    if isinstance(v, ast.expr) else v)
                for k, v in bindings.items()
            },
            fn_node=resolved.node,
        )
        returns = [
            n.value for n in ast.walk(resolved.node)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        values = {
            v for v in (
                self.eval(r, callee_frame, depth + 1) for r in returns
            ) if not isinstance(v, _Unknown)
        }
        return values.pop() if len(values) == 1 else UNKNOWN


def bind_call_args(
    fn_node: ast.AST, call: ast.Call
) -> Optional[Dict[str, object]]:
    """Map a call's argument expressions onto the callee's parameter names
    (positional + keyword + defaults). None when the shapes don't line up
    (starargs, **kwargs, too many positionals)."""
    args = getattr(fn_node, "args", None)
    if args is None:
        return None
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    if any(kw.arg is None for kw in call.keywords):
        return None
    params = [a.arg for a in args.args]
    if params and params[0] == "self":
        params = params[1:]
    out: Dict[str, object] = {}
    if len(call.args) > len(params):
        return None
    for name, expr in zip(params, call.args):
        out[name] = expr
    for kw in call.keywords:
        if kw.arg in params:
            out[kw.arg] = kw.value
    # Defaults for parameters the call leaves unset.
    defaults = args.defaults or []
    for param_ast, default in zip(args.args[-len(defaults):], defaults):
        name = param_ast.arg
        if name != "self" and name not in out:
            out[name] = default
    for name in params:
        out.setdefault(name, UNKNOWN)
    return out


@dataclasses.dataclass(frozen=True)
class PlanePut:
    """One `jax.device_put` of a named state plane under a resolved spec."""

    rel: str
    line: int
    plane: str      # trailing attribute chain: "tok", "cache.length"
    spec: object    # "P(...)" | UNKNOWN


def _plane_key(expr: ast.expr) -> Optional[str]:
    """'cache.length' from `state.cache.length`: the plane identity is the
    attribute chain past the root binding (which is just a local name)."""
    chain = chain_str(expr)
    if chain is None or "." not in chain:
        return None
    root, rest = chain.split(".", 1)
    if root == "self" and "." in rest:
        # self.state.tok -> plane past the attribute root.
        rest = rest.split(".", 1)[1]
    return rest or None


def _is_device_put(call: ast.Call) -> bool:
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else ""
    )
    return name == "device_put"


def collect_plane_puts(
    project: Project, prefixes: Sequence[str] = (ENGINE_PREFIX,)
) -> List[PlanePut]:
    """Every device_put of a named plane in the watched modules, with the
    spec it lands under — one level of nested-helper indirection resolved
    by binding the helper's parameters at each of its call sites (the
    `paged._canon_state.put(state.tok)` shape)."""
    puts: List[PlanePut] = []
    for rel, mod in sorted(project.modules.items()):
        if not any(rel.startswith(p) for p in prefixes):
            continue
        ev = SpecEval(project, mod)
        for node in ast.walk(mod.src.tree):
            if not isinstance(node, ast.Call) or not _is_device_put(node):
                continue
            if len(node.args) < 2:
                continue
            value_expr, spec_expr = node.args[0], node.args[1]
            fn_node = enclosing_function(mod.src.parents(node))
            if fn_node is None:
                continue
            if isinstance(value_expr, ast.Attribute):
                plane = _plane_key(value_expr)
                if plane is None:
                    continue
                frame = Frame(bindings={}, fn_node=fn_node)
                puts.append(PlanePut(
                    rel=rel, line=node.lineno, plane=plane,
                    spec=ev.eval(spec_expr, frame),
                ))
                continue
            if not isinstance(value_expr, ast.Name):
                continue
            # `device_put(x, ...)` where x is a parameter of a nested
            # helper: bind each call site's actuals and evaluate there.
            params = {
                a.arg for a in getattr(fn_node, "args", ast.arguments(
                    args=[], posonlyargs=[], kwonlyargs=[], kw_defaults=[],
                    defaults=[],
                )).args
            }
            parent_fn = enclosing_function(mod.src.parents(fn_node))
            if value_expr.id not in params or parent_fn is None:
                continue
            helper_name = getattr(fn_node, "name", None)
            for site in ast.walk(parent_fn):
                if not isinstance(site, ast.Call):
                    continue
                if not (
                    isinstance(site.func, ast.Name)
                    and site.func.id == helper_name
                ):
                    continue
                bindings = bind_call_args(fn_node, site)
                if bindings is None:
                    continue
                actual = bindings.get(value_expr.id)
                if not isinstance(actual, ast.expr):
                    continue
                plane = _plane_key(actual)
                if plane is None:
                    continue
                outer = Frame(bindings={}, fn_node=parent_fn)
                frame = Frame(
                    bindings={
                        k: (ev.eval(v, outer)
                            if isinstance(v, ast.expr) else v)
                        for k, v in bindings.items()
                    },
                    fn_node=fn_node, parent=outer,
                )
                puts.append(PlanePut(
                    rel=rel, line=site.lineno, plane=plane,
                    spec=ev.eval(spec_expr, frame),
                ))
    return puts


# ------------------------------------------------------------- dtype flow


_FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16"}
_INT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint32"}
_DTYPE_NAMES = _FLOAT_DTYPES | _INT_DTYPES | {"bool_", "bool"}
WEAK_INT = "weak_int"
WEAK_FLOAT = "weak_float"


def dtype_of_node(node: ast.expr) -> Optional[str]:
    """'int8' for `jnp.int8` / `np.int8` / `"int8"`; None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
        return node.attr
    if isinstance(node, ast.Name) and node.id in _DTYPE_NAMES:
        return node.id
    return None


# jnp constructors: name -> index of the positional dtype argument.
_CTOR_DTYPE_POS = {
    "zeros": 1, "ones": 1, "empty": 1, "full": 2, "asarray": 1, "array": 1,
}


class DtypeWalker:
    """Forward dtype propagation through one function body.

    `on_upcast(node, src_dtype, dst_dtype)` fires on `.astype()` from int8
    to a float dtype; `on_weak_promotion(node, dtype)` fires when a
    known-int-dtype array meets a bare float literal (jax weak-type
    promotion silently widens the array to the default float dtype).
    Functions whose name mentions dequantization are exempt from the
    upcast hook — converting back to compute precision is their job.
    """

    def __init__(
        self,
        project: Project,
        on_upcast: Callable[[ast.AST, str, str], None],
        on_weak_promotion: Callable[[ast.AST, str], None],
    ):
        self.project = project
        self.on_upcast = on_upcast
        self.on_weak_promotion = on_weak_promotion
        self._return_cache: Dict[str, Optional[str]] = {}
        self._in_progress: Set[str] = set()
        self._last_inferred: Dict[int, Optional[str]] = {}
        # >0 while evaluating a CALLEE for its return dtype: the callee is
        # (or will be) walked directly under its own module, so findings
        # made during the quiet pass would be mis-attributed — drop them.
        self._quiet = 0

    # -- public entry ----------------------------------------------------

    def run(self, fn: FunctionInfo) -> None:
        allow_upcast = "dequant" in fn.name.lower()
        env: Dict[str, str] = {}
        for stmt in getattr(fn.node, "body", []):
            self._stmt(stmt, env, fn, allow_upcast)

    # -- statements ------------------------------------------------------

    def _stmt(
        self, stmt: ast.AST, env: Dict[str, str], fn: FunctionInfo,
        allow_upcast: bool,
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs run via their own FunctionInfo
        if isinstance(stmt, ast.Assign):
            val = self._infer(stmt.value, env, fn, allow_upcast)
            self._bind_targets(stmt.targets, stmt.value, val, env)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            val = self._infer(stmt.value, env, fn, allow_upcast)
            self._bind_targets([stmt.target], stmt.value, val, env)
            return
        if isinstance(stmt, ast.AugAssign):
            self._infer(stmt.value, env, fn, allow_upcast)
            chain = chain_str(stmt.target)
            if chain is not None:
                env.pop(chain, None)
            return
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if getattr(stmt, "value", None) is not None:
                self._infer(stmt.value, env, fn, allow_upcast)
            return
        # Compound statement: guard expressions see the pre-branch env...
        for field in ("test", "iter", "items"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, ast.expr):
                self._infer(sub, env, fn, allow_upcast)
        # ...and each block runs on its OWN copy — bindings made inside a
        # branch must not leak into a mutually-exclusive sibling (an
        # if-body's `x = int8` would otherwise invent findings on the
        # else-path's float `x`) nor survive past a block that may not
        # execute (if-without-else, zero-iteration loops). The pristine
        # env joins the merge as the "no block ran" path, so only
        # bindings NO branch touched survive — maximally conservative:
        # facts are lost, never invented.
        branch_envs = [dict(env)]
        for field in _BLOCK_FIELDS:
            seq = getattr(stmt, field, []) or []
            if not seq:
                continue
            benv = dict(branch_envs[0])
            for child in seq:
                self._stmt(child, benv, fn, allow_upcast)
            branch_envs.append(benv)
        env.clear()
        env.update({
            k: v for k, v in branch_envs[0].items()
            if all(b.get(k) == v for b in branch_envs[1:])
        })

    def _bind_targets(
        self, targets: List[ast.expr], value: ast.expr,
        val: Optional[str], env: Dict[str, str],
    ) -> None:
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                if isinstance(value, (ast.Tuple, ast.List)) and len(
                    value.elts
                ) == len(t.elts):
                    for sub_t, sub_v in zip(t.elts, value.elts):
                        self._bind_targets(
                            [sub_t], sub_v, self._last_inferred.get(
                                id(sub_v)
                            ), env,
                        )
                else:
                    for sub_t in t.elts:
                        chain = chain_str(sub_t)
                        if chain is not None:
                            env.pop(chain, None)
                continue
            chain = chain_str(t)
            if chain is None:
                continue
            if val is None:
                env.pop(chain, None)
            else:
                env[chain] = val

    # -- expressions -----------------------------------------------------

    def _infer(
        self, expr: ast.expr, env: Dict[str, str], fn: FunctionInfo,
        allow_upcast: bool, depth: int = 0,
    ) -> Optional[str]:
        out = self._infer_inner(expr, env, fn, allow_upcast, depth)
        self._last_inferred[id(expr)] = out
        return out

    def _infer_inner(
        self, expr: ast.expr, env: Dict[str, str], fn: FunctionInfo,
        allow_upcast: bool, depth: int,
    ) -> Optional[str]:
        if depth > _MAX_DEPTH:
            return None
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, bool):
                return "bool"
            if isinstance(expr.value, int):
                return WEAK_INT
            if isinstance(expr.value, float):
                return WEAK_FLOAT
            return None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            chain = chain_str(expr)
            return env.get(chain) if chain is not None else None
        if isinstance(expr, ast.UnaryOp):
            return self._infer(expr.operand, env, fn, allow_upcast, depth + 1)
        if isinstance(expr, ast.Subscript):
            return self._infer(expr.value, env, fn, allow_upcast, depth + 1)
        if isinstance(expr, ast.IfExp):
            a = self._infer(expr.body, env, fn, allow_upcast, depth + 1)
            b = self._infer(expr.orelse, env, fn, allow_upcast, depth + 1)
            return a if a == b else None
        if isinstance(expr, ast.BinOp):
            return self._infer_binop(expr, env, fn, allow_upcast, depth)
        if isinstance(expr, ast.Call):
            return self._infer_call(expr, env, fn, allow_upcast, depth)
        # Anything else: walk children for side-effect findings.
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._infer(child, env, fn, allow_upcast, depth + 1)
        return None

    def _infer_binop(
        self, expr: ast.BinOp, env: Dict[str, str], fn: FunctionInfo,
        allow_upcast: bool, depth: int,
    ) -> Optional[str]:
        left = self._infer(expr.left, env, fn, allow_upcast, depth + 1)
        right = self._infer(expr.right, env, fn, allow_upcast, depth + 1)
        for strong, weak in ((left, right), (right, left)):
            if strong in _INT_DTYPES and weak == WEAK_FLOAT:
                if not self._quiet:
                    self.on_weak_promotion(expr, strong)
                return "float32"
        if left == right:
            return left
        if {left, right} <= (_INT_DTYPES | {WEAK_INT}):
            known = [d for d in (left, right) if d in _INT_DTYPES]
            return known[0] if len(known) == 1 else None
        if isinstance(expr.op, ast.Div):
            return None  # true division promotes to float; dtype unclear
        return None

    def _infer_call(
        self, expr: ast.Call, env: Dict[str, str], fn: FunctionInfo,
        allow_upcast: bool, depth: int,
    ) -> Optional[str]:
        for a in expr.args:
            self._infer(a, env, fn, allow_upcast, depth + 1)
        for kw in expr.keywords:
            self._infer(kw.value, env, fn, allow_upcast, depth + 1)
        func = expr.func
        if isinstance(func, ast.Attribute):
            if func.attr == "astype":
                base = self._infer(
                    func.value, env, fn, allow_upcast, depth + 1
                )
                dst: Optional[str] = None
                if expr.args:
                    dst = dtype_of_node(expr.args[0])
                for kw in expr.keywords:
                    if kw.arg == "dtype":
                        dst = dtype_of_node(kw.value)
                if (
                    base == "int8" and dst in _FLOAT_DTYPES
                    and not allow_upcast and not self._quiet
                ):
                    self.on_upcast(expr, base, dst)
                return dst
            ns = func.value
            if isinstance(ns, ast.Name) and ns.id in ("jnp", "np", "numpy"):
                name = func.attr
                if name.endswith("_like") and expr.args:
                    return self._infer(
                        expr.args[0], env, fn, allow_upcast, depth + 1
                    )
                if name == "where" and len(expr.args) == 3:
                    a = self._infer(
                        expr.args[1], env, fn, allow_upcast, depth + 1
                    )
                    b = self._infer(
                        expr.args[2], env, fn, allow_upcast, depth + 1
                    )
                    return a if a == b else None
                if name in _CTOR_DTYPE_POS:
                    for kw in expr.keywords:
                        if kw.arg == "dtype":
                            return dtype_of_node(kw.value)
                    pos = _CTOR_DTYPE_POS[name]
                    if len(expr.args) > pos:
                        return dtype_of_node(expr.args[pos])
                    if name in ("asarray", "array") and expr.args:
                        return self._infer(
                            expr.args[0], env, fn, allow_upcast, depth + 1
                        )
                return None
        # Project-local call: memoized return dtype (context-insensitive).
        mod = self.project.modules.get(fn.rel)
        if mod is None:
            return None
        resolved = self.project.resolve_call(mod, func, fn.class_name, fn)
        if resolved is None:
            return None
        return self._return_dtype(resolved, depth)

    def _return_dtype(self, fn: FunctionInfo, depth: int) -> Optional[str]:
        if fn.qname in self._return_cache:
            return self._return_cache[fn.qname]
        if fn.qname in self._in_progress or depth > _MAX_DEPTH:
            return None
        self._in_progress.add(fn.qname)
        self._quiet += 1
        try:
            env: Dict[str, str] = {}
            allow = "dequant" in fn.name.lower()
            values: Set[Optional[str]] = set()
            for stmt in getattr(fn.node, "body", []):
                self._stmt(stmt, env, fn, allow)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    values.add(
                        self._infer(node.value, env, fn, allow, depth + 1)
                    )
            out = values.pop() if len(values) == 1 else None
        finally:
            self._in_progress.discard(fn.qname)
            self._quiet -= 1
        self._return_cache[fn.qname] = out
        return out
