"""Framework for repo-native AST lint rules.

Generalizes the `scripts/audit_markers.py` pattern (one ad-hoc AST walk +
a tier-1 test pinning the tree clean) into a registry of rules sharing:

- one parse per file (`Source` carries text, lines, AST with parent links);
- a suppression grammar (`# lint: disable=<rule>[,<rule>...]` on the
  offending line, `# lint: disable-next=...` on the line above, or
  `# lint: disable-file=...` anywhere) so sanctioned exceptions are
  visible and attributable instead of silently special-cased in the rule;
- a runner (`run_lint`) that `scripts/lint.py` and
  `tests/test_lint_clean.py` share, so CI and the CLI can never disagree
  about what "clean" means.

Rules are pure AST/text analyses — nothing here imports the modules it
checks, so the linter runs in milliseconds and cannot be broken by import
side effects (JAX backend init, gRPC codegen, ...).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

# Matches "# lint: disable=a,b" / "disable-next=" / "disable-file=".
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*(disable(?:-next|-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)"
)

# Files never worth scanning: generated protobuf blobs and the lint
# fixture corpus (known-bad snippets exercised by tests/test_lint_rules.py).
EXCLUDE_PARTS = ("lint_fixtures",)
EXCLUDE_NAMES = ("lms_pb2.py",)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # repo-relative, '/'-separated
    line: int      # 1-based
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class Source:
    """One parsed file: text, AST (with `.parent` links), suppressions."""

    def __init__(self, path: Path, root: Optional[Path] = None,
                 text: Optional[str] = None):
        self.path = Path(path)
        root = Path(root) if root is not None else None
        try:
            self.rel = (
                self.path.resolve().relative_to(root.resolve()).as_posix()
                if root is not None
                else self.path.as_posix()
            )
        except ValueError:
            self.rel = self.path.as_posix()
        self.text = text if text is not None else self.path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(self.path))
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child.parent = node  # type: ignore[attr-defined]
        self._line_suppressions: Dict[int, Set[str]] = {}
        self._file_suppressions: Set[str] = set()
        self._parse_suppressions()

    def _parse_suppressions(self) -> None:
        for lineno, line in enumerate(self.lines, start=1):
            for mode, names in _SUPPRESS_RE.findall(line):
                rules = {n.strip() for n in names.split(",") if n.strip()}
                if mode == "disable-file":
                    self._file_suppressions |= rules
                elif mode == "disable-next":
                    self._line_suppressions.setdefault(
                        lineno + 1, set()
                    ).update(rules)
                else:
                    self._line_suppressions.setdefault(lineno, set()).update(
                        rules
                    )

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppressions:
            return True
        return rule in self._line_suppressions.get(line, set())

    # Convenience used by rules.
    def parents(self, node: ast.AST) -> Iterable[ast.AST]:
        """Ancestors of `node`, innermost first."""
        cur = getattr(node, "parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "parent", None)


class Rule:
    """One check. Subclasses set `name`/`description` and implement
    `check(src) -> [Finding]`; `applies_to(rel_path)` scopes which files
    the runner hands them (tests may call `check` directly on any Source,
    which is how fixture snippets exercise path-scoped rules)."""

    name: str = ""
    description: str = ""

    def applies_to(self, rel: str) -> bool:
        return rel.endswith(".py")

    def check(self, src: Source) -> List[Finding]:
        raise NotImplementedError

    def finding(self, src: Source, node_or_line, message: str) -> Finding:
        line = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 0)
        )
        return Finding(rule=self.name, path=src.rel, line=line, message=message)


_REGISTRY: List[Rule] = []


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding an instance to the global rule registry."""
    _REGISTRY.append(cls())
    return cls


def all_rules() -> List[Rule]:
    return list(_REGISTRY)


def repo_root() -> Path:
    """The repository root (two levels above this package)."""
    return Path(__file__).resolve().parent.parent.parent


def default_paths(root: Optional[Path] = None) -> List[Path]:
    """What a full run covers: the package, the scripts, and the tests."""
    root = root or repo_root()
    return [
        root / "distributed_lms_raft_llm_tpu",
        root / "scripts",
        root / "tests",
    ]


# Shared parse cache: one entry per (root, path) serves every consumer in
# the process — the per-file rule pass, the project model's full-tree
# build on subset runs (which used to re-parse everything the subset pass
# had just parsed), and repeated run_lint() calls from tests. Sources are
# immutable after construction, so sharing is safe; the stat signature in
# the VALUE makes a file edit replace the stale entry instead of leaking
# it (long-lived processes — watch loops, daemons — stay bounded at one
# Source per file).
_SOURCE_CACHE: Dict[Tuple[str, str], Tuple[Tuple[int, int], Source]] = {}


def _cached_source(path: Path, root: Path) -> Source:
    try:
        st = path.stat()
    except OSError:
        return Source(path, root=root)
    key = (str(root), str(path))
    sig = (st.st_mtime_ns, st.st_size)
    hit = _SOURCE_CACHE.get(key)
    if hit is not None and hit[0] == sig:
        return hit[1]
    src = Source(path, root=root)
    _SOURCE_CACHE[key] = (sig, src)
    return src


def iter_sources(
    paths: Optional[Sequence[Path]] = None, root: Optional[Path] = None
) -> List[Source]:
    root = root or repo_root()
    out: List[Source] = []
    for base in paths or default_paths(root):
        base = Path(base)
        files = sorted(base.rglob("*.py")) if base.is_dir() else [base]
        for path in files:
            if path.name in EXCLUDE_NAMES:
                continue
            if any(part in EXCLUDE_PARTS for part in path.parts):
                continue
            out.append(_cached_source(path, root))
    return out


def run_lint(
    paths: Optional[Sequence[Path]] = None,
    rules: Optional[Sequence[Rule]] = None,
    root: Optional[Path] = None,
) -> List[Finding]:
    """Run `rules` (default: all registered) over `paths` (default: the
    package + scripts + tests). Returns unsuppressed findings, sorted.

    Per-file rules see exactly the requested sources. Project rules
    (`analysis.project.ProjectRule`) always analyze the FULL default tree
    — a call graph over half a repo proves nothing — but only report
    findings inside the requested paths, so `scripts/lint.py engine/`
    stays scoped; absence-style rules (`full_project_only`) additionally
    skip subset runs entirely rather than report on partial evidence.
    """
    from .project import Project, ProjectRule  # local: avoids import cycle

    root = root or repo_root()
    active = list(rules) if rules is not None else all_rules()
    file_rules = [r for r in active if not isinstance(r, ProjectRule)]
    project_rules = [r for r in active if isinstance(r, ProjectRule)]
    selected = iter_sources(paths, root=root)
    findings: List[Finding] = []
    for src in selected:
        for rule in file_rules:
            if not rule.applies_to(src.rel):
                continue
            for f in rule.check(src):
                if not src.suppressed(f.rule, f.line):
                    findings.append(f)
    full_run = paths is None
    # Absence-style rules are filtered BEFORE the (repo-wide) project
    # build, so a scoped run whose project rules would all skip doesn't
    # parse the whole tree for nothing.
    project_rules = [
        r for r in project_rules if full_run or not r.full_project_only
    ]
    if project_rules:
        sources = selected if full_run else iter_sources(None, root=root)
        project = Project(sources, root=root)
        selected_rels = {src.rel for src in selected}
        for rule in project_rules:
            for f in rule.check_project(project):
                src = project.sources.get(f.path)
                if src is not None and src.suppressed(f.rule, f.line):
                    continue
                # Findings on files outside the requested subset (or on
                # non-Python artifacts like configs/*.toml) surface only
                # on full runs.
                if not full_run and f.path not in selected_rels:
                    continue
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
