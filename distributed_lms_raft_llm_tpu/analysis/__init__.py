"""dlrl-lint: repo-native static analysis for this codebase's bug classes.

The two most expensive latent bugs this repo shipped were invisible to
tests: a silent recompile-per-request from two spellings of the same
replicated `PartitionSpec` (engine/paged._state_spec history), and
resilience findings that sat unnoticed in `lms/service.py`. Production
stacks encode such invariants as custom lint rules and runtime guards, not
folklore — this package is the static half (the runtime half lives in
`utils/guards.py`).

Usage:
    python scripts/lint.py [--json] [--rule NAME] [paths...]

or in-process:
    from distributed_lms_raft_llm_tpu.analysis import run_lint
    findings = run_lint()

Suppressions (see core.py for the grammar):
    x = bad_thing()        # lint: disable=rule-name
    # lint: disable-next=rule-name
    x = bad_thing()
    # lint: disable-file=rule-name        (anywhere in the file)
"""

from .core import (  # noqa: F401
    Finding,
    Rule,
    Source,
    all_rules,
    default_paths,
    iter_sources,
    register,
    run_lint,
)
from . import rules  # noqa: F401  (importing registers every rule)
