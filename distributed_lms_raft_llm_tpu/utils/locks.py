"""Runtime lock-order enforcement — the dynamic counterpart of the
``lock-order`` lint rule (analysis/rules/lock_order.py).

The static rule proves the repo's lock-*acquisition-order graph* acyclic
from source; this module records the graph the process *actually* walks,
so the two can be cross-checked. :class:`OrderedLock` wraps a
non-reentrant ``threading.Lock`` under a name matching the analysis's
short lock key (``"ClassName._lock"`` — the declaration site). With
recording enabled (the ``ordered_locks`` test fixture; the semester
sim), every successful acquisition:

- pushes the name onto a per-thread held stack,
- adds one ``held -> acquired`` edge per lock already held on this
  thread to the process-wide acquisition graph,
- records a violation if the lock is already held by this thread
  (re-entry on a non-reentrant lock — the PR-13 self-deadlock would be
  caught here *before* wedging, because detection happens while the
  ``acquire`` is still pending), or if the new edge closes a cycle.

Violations are *recorded*, never raised, on the production path: a
serving thread mid-request must degrade, not die. They surface three
ways: :func:`violations` (the sim audit and the ``ordered_locks``
fixture assert it empty), :func:`assert_acyclic` (hard assert for
tests), and the ``lock_order_violations`` counter on whatever metrics
sink :func:`set_metrics_sink` installed.

``make_lock(name)`` is the declaration-site spelling. Recording off
costs one module-global boolean check per acquire; the wrapper is
otherwise a plain ``threading.Lock``. The concurrency engine's
``_LOCK_CTORS`` treats both spellings as threading locks, so converting
a declaration keeps every static rule's view unchanged.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = [
    "OrderedLock",
    "make_lock",
    "recording",
    "enable_recording",
    "disable_recording",
    "reset",
    "acquisition_edges",
    "violations",
    "assert_acyclic",
    "set_metrics_sink",
]

# Process-wide debug state. `_graph` maps lock name -> set of lock names
# acquired while it was held. Guarded by `_state_lock` (a plain leaf
# lock: nothing is ever acquired while holding it, so it cannot
# participate in the ordering it audits).
_state_lock = threading.Lock()
_recording = False
_graph: Dict[str, Set[str]] = {}
_violation_log: List[str] = []
_metrics_sink: Optional[object] = None

_tls = threading.local()


def _held_stack() -> List[str]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


def set_metrics_sink(sink: Optional[object]) -> None:
    """Install a duck-typed metrics object (anything with ``.inc``);
    each recorded violation bumps its ``lock_order_violations`` counter.
    Servers call this at startup; ``None`` detaches."""
    global _metrics_sink
    _metrics_sink = sink


def enable_recording() -> None:
    global _recording
    with _state_lock:
        _recording = True


def disable_recording() -> None:
    global _recording
    with _state_lock:
        _recording = False


def reset() -> None:
    """Clear the recorded graph and violation log (not the held stacks:
    those empty themselves as the owning threads release)."""
    with _state_lock:
        _graph.clear()
        del _violation_log[:]


@contextmanager
def recording() -> Iterator[None]:
    """Scoped recording for tests: enable, run, disable — the recorded
    graph and violations stay readable after exit for assertions."""
    enable_recording()
    try:
        yield
    finally:
        disable_recording()


def acquisition_edges() -> Set[Tuple[str, str]]:
    """Snapshot of the live ``held -> acquired`` edge set."""
    with _state_lock:
        return {(src, dst) for src, dsts in _graph.items() for dst in dsts}


def violations() -> List[str]:
    with _state_lock:
        return list(_violation_log)


def _find_cycle() -> Optional[List[str]]:
    """One cycle in the recorded graph as a name path, or None.
    Iterative coloring DFS, sorted neighbors — deterministic output."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    for root in sorted(_graph):
        if color.get(root, WHITE) != WHITE:
            continue
        # (node, remaining-neighbors) stack; path mirrors the gray chain.
        stack: List[Tuple[str, List[str]]] = [
            (root, sorted(_graph.get(root, ())))
        ]
        color[root] = GRAY
        path = [root]
        while stack:
            node, nbrs = stack[-1]
            if nbrs:
                nxt = nbrs.pop(0)
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    return path[path.index(nxt):] + [nxt]
                if c == WHITE:
                    color[nxt] = GRAY
                    path.append(nxt)
                    stack.append((nxt, sorted(_graph.get(nxt, ()))))
            else:
                color[node] = BLACK
                path.pop()
                stack.pop()
    return None


def assert_acyclic() -> None:
    """Hard assertion for tests: no recorded violations, and the live
    acquisition graph has no cycle (belt-and-braces — a cycle whose
    closing edge raced two threads is caught here even if each edge
    looked fine when added)."""
    with _state_lock:
        if _violation_log:
            raise AssertionError(
                "lock-order violations recorded: " + "; ".join(_violation_log)
            )
        cycle = _find_cycle()
        if cycle is not None:
            raise AssertionError(
                "lock acquisition graph has a cycle: " + " -> ".join(cycle)
            )


def _record_violation(message: str) -> None:
    # Caller holds _state_lock.
    _violation_log.append(message)
    metrics = _metrics_sink
    if metrics is not None:
        try:
            metrics.inc("lock_order_violations")  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001 — auditing must not break serving
            pass


class OrderedLock:
    """A named, non-reentrant ``threading.Lock`` that feeds the live
    acquisition graph when recording is enabled. Name it after the
    declaration site (``"ClassName._lock"``) so the runtime graph lines
    up with ``ConcurrencyEngine.static_order_shorts()``."""

    __slots__ = ("_name", "_lock")

    def __init__(self, name: str) -> None:
        self._name = name
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def _note_acquired(self) -> None:
        held = _held_stack()
        # Unlocked fast-path read: a stale False skips at most the edges
        # of acquisitions racing enable_recording() itself.
        if not _recording:
            held.append(self._name)
            return
        with _state_lock:
            if _recording:
                if self._name in held:
                    # The acquire below would self-deadlock; record it
                    # NOW so the hang is diagnosable from the log.
                    _record_violation(
                        f"re-entry: {self._name} acquired while already "
                        f"held by this thread (held: {held})"
                    )
                for h in held:
                    if h == self._name:
                        continue
                    dsts = _graph.setdefault(h, set())
                    if self._name not in dsts:
                        dsts.add(self._name)
                        cycle = _find_cycle()
                        if cycle is not None:
                            _record_violation(
                                f"cycle closed by {h} -> {self._name}: "
                                + " -> ".join(cycle)
                            )
        held.append(self._name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Edges are recorded BEFORE the blocking acquire so that the
        # acquisition that wedges a thread is already in the graph and
        # the violation log names it.
        self._note_acquired()
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            self._unwind()
        return ok

    def _unwind(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._name:
                del held[i]
                break

    def release(self) -> None:
        self._lock.release()
        self._unwind()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        state = "locked" if self._lock.locked() else "unlocked"
        return f"<OrderedLock {self._name} {state}>"


def make_lock(name: str) -> OrderedLock:
    """Declaration-site factory: ``self._lock = make_lock("Cls._lock")``.
    Always returns an :class:`OrderedLock`; with recording disabled the
    overhead is one boolean check per acquisition."""
    return OrderedLock(name)
