"""Cross-cutting utilities: config, logging, metrics, tokenizers."""
