"""Cluster scrape aggregator: many nodes' `/metrics` -> one timeline.

One node's `TimelineSampler` (utils/timeline.py) answers "what is THIS
process doing over time"; operators and the continuous SLO engine need
the CLUSTER answer — every node's `/metrics` polled on one clock and
merged into a single timeline the windowed queries run over. The merge
rules are the boring-but-load-bearing part:

- **Counters** merge as summed per-node DELTAS, not summed values: each
  source keeps its own last-seen cumulative counters, a value that went
  backwards (the node restarted and wiped them) contributes its whole
  new value (the Prometheus reset rule), and an unreachable node simply
  contributes nothing that round — so a rolling restart reads as a blip
  in the rate, never as a negative spike or a cliff in the sum.
- **Gauges** merge as the worst (max) across nodes: breaker state, queue
  depth, `storage_recovering` — the cluster is as unhealthy as its
  unhealthiest node.
- **Histograms** merge by worst p95: the cluster-level `llm_ttft` block
  is the reporting node with the slowest tail, which is what an SLO
  bound cares about — except `count`, which is accumulated per-source
  like a counter so it stays monotonic when the worst node flips
  (Timeline's dcount/hist_rate depend on that).

Sources are either URLs (`http_source`, stdlib urllib, short timeout,
errors tolerated and counted) or plain callables returning a snapshot
dict — the semester sim feeds its own client-side `Metrics` and the
in-process tutoring queue through the same path its HTTP nodes take.
`scripts/telemetry.py` wraps this in a live dashboard + JSON export.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from .timeline import Snapshot, Timeline

SourceFn = Callable[[], Optional[Snapshot]]


def http_source(url: str, timeout_s: float = 2.0) -> SourceFn:
    """A `/metrics` poller for one node; None (not an exception) when the
    node is unreachable — restarts mid-poll are normal operations."""
    if not url.endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"

    def fetch() -> Optional[Snapshot]:
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                doc = json.loads(resp.read().decode())
            return doc if isinstance(doc, dict) else None
        except (urllib.error.URLError, OSError, ValueError):
            return None

    return fetch


class ClusterScraper:
    """Polls every source into per-node timelines + one merged cluster
    timeline. Single-threaded by design: call `poll()` from one loop (the
    harness telemetry thread, the CLI's main loop)."""

    def __init__(
        self,
        sources: Optional[Dict[str, SourceFn]] = None,
        sources_fn: Optional[Callable[[], Dict[str, SourceFn]]] = None,
        max_points: int = 2048,
    ):
        if (sources is None) == (sources_fn is None):
            raise ValueError("pass exactly one of sources / sources_fn")
        self._sources = dict(sources or {})
        self._sources_fn = sources_fn
        self._max_points = max_points
        self.cluster = Timeline(max_points=max_points)
        self.nodes: Dict[str, Timeline] = {}
        self.unreachable: Dict[str, int] = {}
        # Per-source last-seen cumulative counters / histogram counts
        # (reset detection) and the merged monotonic accumulators the
        # cluster timeline is fed.
        self._prev: Dict[str, Dict[str, int]] = {}
        self._prev_hist: Dict[str, Dict[str, int]] = {}
        self._cum: Dict[str, int] = {}
        self._hist_cum: Dict[str, int] = {}
        self._last_node_count = 0

    # ------------------------------------------------------------ polling

    def _resolve(self) -> Dict[str, SourceFn]:
        if self._sources_fn is not None:
            # Re-resolved every poll: membership adds/removes change the
            # scrape set mid-run.
            return dict(self._sources_fn())
        return self._sources

    def poll(self, now: Optional[float] = None) -> Snapshot:
        """One scrape round; returns the merged cluster snapshot that was
        appended to `self.cluster`."""
        t = time.time() if now is None else now
        merged_gauges: Dict[str, float] = {}
        merged_hists: Dict[str, Dict[str, float]] = {}
        reachable = 0
        sources = self._resolve()
        for name, fetch in sources.items():
            snap = fetch()
            if snap is None:
                self.unreachable[name] = self.unreachable.get(name, 0) + 1
                continue
            reachable += 1
            node_tl = self.nodes.get(name)
            if node_tl is None:
                node_tl = self.nodes[name] = Timeline(
                    max_points=self._max_points
                )
            node_tl.append(snap, t=t)
            first_sight = name not in self._prev
            prev = self._prev.setdefault(name, {})
            for cname, raw in snap.get("counters", {}).items():
                cur = int(raw)
                seen = prev.get(cname, 0)
                prev[cname] = cur
                if first_sight:
                    # First sample of a source only seeds its baselines
                    # (the Prometheus two-samples-for-a-rate rule): its
                    # boot-era totals must not read as a rate spike in
                    # the first window.
                    continue
                delta = cur - seen if cur >= seen else cur
                self._cum[cname] = self._cum.get(cname, 0) + delta
            for gname, raw_g in snap.get("gauges", {}).items():
                val = float(raw_g)
                if gname not in merged_gauges or val > merged_gauges[gname]:
                    merged_gauges[gname] = val
            prev_hist = self._prev_hist.setdefault(name, {})
            for hname, block in snap.get("latency", {}).items():
                if not isinstance(block, dict):
                    continue
                cur_n = int(block.get("count", 0))
                seen_n = prev_hist.get(hname, 0)
                prev_hist[hname] = cur_n
                if not first_sight:
                    self._hist_cum[hname] = self._hist_cum.get(
                        hname, 0
                    ) + (cur_n - seen_n if cur_n >= seen_n else cur_n)
                worst = merged_hists.get(hname)
                if worst is None or float(block.get("p95_s", 0.0)) > float(
                    worst.get("p95_s", 0.0)
                ):
                    merged_hists[hname] = {
                        k: float(v) for k, v in block.items()
                    }
        self._last_node_count = len(sources)
        # The merged block keeps the worst node's percentiles, but its
        # `count` must be the cluster-cumulative observation count
        # (accumulated per-source like counters): a per-node count would
        # jump whenever the worst node flips, and Timeline.append would
        # misread the jumps as resets — garbage dcount/hist_rate.
        for hname, block in merged_hists.items():
            block["count"] = float(self._hist_cum.get(hname, 0))
        cluster_snap: Snapshot = {
            "counters": dict(self._cum),
            "gauges": merged_gauges,
            "latency": merged_hists,
        }
        self.cluster.append(cluster_snap, t=t)
        return cluster_snap

    # ------------------------------------------------------------- export

    @property
    def node_count(self) -> int:
        return self._last_node_count

    def export(self) -> Dict[str, object]:
        """One JSON document: the merged cluster timeline, every per-node
        timeline, and the scrape bookkeeping — the artifact
        `scripts/telemetry.py --capacity` fits the capacity model over."""
        return {
            "node_count": self._last_node_count,
            "unreachable": dict(self.unreachable),
            "cluster": self.cluster.to_dict(),
            "nodes": {name: tl.to_dict() for name, tl in self.nodes.items()},
        }


def endpoints_sources(endpoints: List[str],
                      timeout_s: float = 2.0) -> Dict[str, SourceFn]:
    """URL list -> named source map (the CLI's --endpoint plumbing)."""
    out: Dict[str, SourceFn] = {}
    for ep in endpoints:
        name = ep.rstrip("/").rsplit("//", 1)[-1]
        out[name] = http_source(ep, timeout_s=timeout_s)
    return out
