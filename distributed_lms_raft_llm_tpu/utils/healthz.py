"""Minimal HTTP health/metrics endpoint (stdlib asyncio, no deps).

The reference's only "health" signal is the WhoIsLeader RPC, and metrics
lived in periodic log lines. This exposes the same Metrics snapshot and a
liveness/role summary over plain HTTP so operators (and the bench harness)
can scrape without a gRPC client:

    GET /healthz  -> {"ok": true, "role": "leader", ...}
    GET /metrics  -> the Metrics.snapshot() JSON
    GET /metrics.prom -> the same snapshot in Prometheus text exposition
                     (utils/timeline.render_prometheus: name/kind/help
                     from utils/metrics_registry.py), so a stock
                     Prometheus/VictoriaMetrics scraper ingests every
                     node with zero glue
    POST /admin/* -> optional admin hook (e.g. cluster membership change
                     on the LMS leader: serving/lms_server.py) — JSON body
                     in, JSON out; the admin plane stays off the frozen
                     gRPC wire contract
    GET /admin/*  -> optional READ-ONLY admin hook (`admin_get`), e.g.
                     GET /admin/faults returns the active fault/campaign
                     configuration so operators and the semester simulator
                     can assert what is injected; mutations stay POST-only

Serving is a ~60-line asyncio protocol rather than http.server-in-a-thread
so it shares the node's event loop (single-threaded by construction, like
the Raft runner).
"""

from __future__ import annotations

import asyncio
import json
from typing import Awaitable, Callable, Dict, Optional

from .metrics import Metrics
from .timeline import render_prometheus

Provider = Callable[[], Dict]
# (path, body) -> response dict; raise KeyError for unknown paths,
# ValueError for bad requests.
AdminHandler = Callable[[str, Dict], Awaitable[Dict]]
# path -> response dict for GET /admin/* (read-only introspection; same
# KeyError/ValueError error mapping as the POST handler).
AdminGetHandler = Callable[[str], Awaitable[Dict]]


class HealthServer:
    def __init__(
        self,
        metrics: Metrics,
        *,
        health: Optional[Provider] = None,
        admin: Optional[AdminHandler] = None,
        admin_get: Optional[AdminGetHandler] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.metrics = metrics
        self.health = health or (lambda: {"ok": True})
        self.admin = admin
        self.admin_get = admin_get
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> int:
        """Bind and serve; returns the bound port (for port=0)."""
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 5.0)
            parts = request_line.decode("latin-1").split()
            method = parts[0].upper() if parts else "GET"
            path = parts[1] if len(parts) >= 2 else "/"
            content_length = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    try:
                        content_length = max(0, int(line.split(b":", 1)[1]))
                    except ValueError:
                        pass
            ctype = "application/json"
            if path == "/healthz":
                body, status = json.dumps(self.health()), 200
            elif path == "/metrics":
                body, status = json.dumps(self.metrics.snapshot()), 200
            elif path == "/metrics.prom":
                body = render_prometheus(self.metrics.snapshot())
                status = 200
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif (
                method == "GET"
                and path.startswith("/admin/")
                and self.admin_get is not None
            ):
                try:
                    body, status = json.dumps(await self.admin_get(path)), 200
                except KeyError:
                    body, status = json.dumps({"error": "not found"}), 404
                except ValueError as e:
                    body, status = json.dumps({"error": str(e)}), 400
                except Exception as e:  # surfaced, not swallowed
                    body, status = json.dumps({"error": str(e)}), 500
            elif (
                method == "POST"
                and path.startswith("/admin/")
                and self.admin is not None
            ):
                raw = b""
                if content_length:
                    raw = await asyncio.wait_for(
                        reader.readexactly(min(content_length, 1 << 20)), 5.0
                    )
                try:
                    req = json.loads(raw.decode() or "{}")
                    body, status = json.dumps(await self.admin(path, req)), 200
                except KeyError:
                    body, status = json.dumps({"error": "not found"}), 404
                except ValueError as e:
                    body, status = json.dumps({"error": str(e)}), 400
                except Exception as e:  # surfaced, not swallowed
                    body, status = json.dumps({"error": str(e)}), 500
            else:
                body, status = json.dumps({"error": "not found"}), 404
            payload = body.encode()
            reason = {
                200: "OK", 400: "Bad Request", 404: "Not Found",
                500: "Internal Server Error",
            }.get(status, "Error")
            writer.write(
                (
                    f"HTTP/1.1 {status} {reason}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + payload
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, EOFError):
            # EOFError covers IncompleteReadError: a client that closes
            # mid-body gets no response (its connection is gone anyway).
            pass
        finally:
            writer.close()
            try:
                # Bounded: a pending cancellation must not be able to
                # interrupt the drain and skip the rest of the teardown.
                await asyncio.wait_for(writer.wait_closed(), 1.0)
            except (asyncio.TimeoutError, ConnectionError):
                pass
