"""Minimal PDF text extraction and generation — stdlib only.

The reference extracts assignment text with PyPDF2 at upload time
(reference: GUI_RAFT_LLM_SourceCode/lms_server.py:21-27, used in Post
:918) to feed the BERT relevance gate. This image has no PDF library, so we
implement the small subset needed: walk the file's stream objects,
FlateDecode (zlib) where declared, and collect the text-showing operators
(`Tj`, `'`, and `TJ` arrays) from content streams. Covers the simple
text-based PDFs an LMS deals in; image-only/encrypted PDFs yield "".

`make_pdf` produces a valid single-page PDF from text — used by tests and
the demo client so the whole upload→extract→gate path runs hermetically.
"""

from __future__ import annotations

import re
import zlib
from typing import List

_STREAM_RE = re.compile(rb"<<(.*?)>>\s*stream\r?\n(.*?)\r?\nendstream", re.S)
# () string arguments of text-showing operators, including TJ arrays.
_TJ_RE = re.compile(rb"\((?:\\.|[^\\()])*\)\s*(?:Tj|')|\[(?:[^\]]*)\]\s*TJ")
_STR_RE = re.compile(rb"\((?:\\.|[^\\()])*\)")

_ESCAPES = {
    ord("n"): b"\n", ord("r"): b"\r", ord("t"): b"\t", ord("b"): b"\b",
    ord("f"): b"\f", ord("("): b"(", ord(")"): b")", ord("\\"): b"\\",
}


def _unescape(raw: bytes) -> bytes:
    """Decode PDF string escapes left-to-right, one escape at a time
    (a sequential replace() pass would mis-decode e.g. br'\\\\n')."""
    out = bytearray()
    i = 0
    n = len(raw)
    while i < n:
        c = raw[i]
        if c != 0x5C or i + 1 >= n:  # not a backslash, or trailing one
            out.append(c)
            i += 1
            continue
        nxt = raw[i + 1]
        if nxt in _ESCAPES:
            out += _ESCAPES[nxt]
            i += 2
        elif 0x30 <= nxt <= 0x37:  # octal escape, up to 3 digits
            j = i + 1
            digits = b""
            while j < n and len(digits) < 3 and 0x30 <= raw[j] <= 0x37:
                digits += raw[j : j + 1]
                j += 1
            out.append(int(digits, 8) & 0xFF)
            i = j
        else:  # unknown escape: PDF says drop the backslash
            out.append(nxt)
            i += 2
    return bytes(out)


def _text_from_content(content: bytes) -> List[str]:
    parts: List[str] = []
    for m in _TJ_RE.finditer(content):
        for s in _STR_RE.finditer(m.group(0)):
            raw = _unescape(s.group(0)[1:-1])
            text = raw.decode("latin-1", errors="replace")
            if text:
                parts.append(text)
    return parts


def extract_text(data: bytes) -> str:
    """Best-effort text of a PDF byte string ("" when nothing extractable)."""
    if not data.startswith(b"%PDF"):
        return ""
    parts: List[str] = []
    for m in _STREAM_RE.finditer(data):
        header, body = m.group(1), m.group(2)
        if b"FlateDecode" in header:
            try:
                body = zlib.decompress(body)
            except zlib.error:
                continue
        parts.extend(_text_from_content(body))
    return " ".join(parts).strip()


def extract_text_from_file(path: str) -> str:
    with open(path, "rb") as f:
        return extract_text(f.read())


def make_pdf(text: str, *, title: str = "document") -> bytes:
    """A valid, minimal one-page PDF showing `text` (Helvetica, one line per
    \\n). Round-trips through extract_text."""
    lines = text.split("\n")
    shows = []
    y = 760
    for line in lines:
        esc = line.replace("\\", r"\\").replace("(", r"\(").replace(")", r"\)")
        shows.append(f"BT /F1 12 Tf 60 {y} Td ({esc}) Tj ET")
        y -= 16
    content = "\n".join(shows).encode("latin-1", errors="replace")

    objs = [
        b"<< /Type /Catalog /Pages 2 0 R >>",
        b"<< /Type /Pages /Kids [3 0 R] /Count 1 >>",
        b"<< /Type /Page /Parent 2 0 R /MediaBox [0 0 612 792] "
        b"/Resources << /Font << /F1 5 0 R >> >> /Contents 4 0 R >>",
        b"<< /Length %d >>\nstream\n%s\nendstream" % (len(content), content),
        b"<< /Type /Font /Subtype /Type1 /BaseFont /Helvetica >>",
    ]
    out = bytearray(b"%PDF-1.4\n")
    offsets = [0]
    for i, obj in enumerate(objs, start=1):
        offsets.append(len(out))
        out += b"%d 0 obj\n" % i + obj + b"\nendobj\n"
    xref_pos = len(out)
    out += b"xref\n0 %d\n" % (len(objs) + 1)
    out += b"0000000000 65535 f \n"
    for off in offsets[1:]:
        out += b"%010d 00000 n \n" % off
    out += (
        b"trailer\n<< /Size %d /Root 1 0 R >>\nstartxref\n%d\n%%%%EOF\n"
        % (len(objs) + 1, xref_pos)
    )
    return bytes(out)
