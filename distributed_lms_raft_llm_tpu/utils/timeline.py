"""Telemetry timeline: the time dimension of `/metrics`.

`Metrics.snapshot()` is a point-in-time document — rich, but blind to
*change*: an operator (or the continuous SLO engine in `sim/slo.py`)
needs "requests per second over the last 10 s" and "worst p95 in the
last minute", not "requests since boot". This module adds that axis with
zero dependencies:

- `Timeline` — a bounded ring of `TimelinePoint`s, each derived from one
  snapshot: per-interval counter deltas (reset-aware, so a restarted
  node's wiped counters read as fresh increments, never as negative
  rates), last-value gauges, and the histogram percentile blocks. On
  top, windowed queries: `counter_rate`/`counter_delta` over the last W
  seconds, `gauge_last`/`gauge_percentile`, and `hist_p95` — the WORST
  reservoir p95 observed inside the window (the reservoir is
  cumulative, so this is a conservative over-W bound; the true
  sliding-window quantile for in-process series is
  `LatencyHistogram.window_percentile`). Operational events (burn-rate
  alerts, fault phases) land in a sibling ring via `record_event`, so
  an exported timeline carries its own annotations.
- `TimelineSampler` — a daemon thread that snapshots one process-local
  `Metrics` every `interval_s` into a `Timeline`; each node serves its
  ring read-only at `GET /admin/timeline`. The sampler self-accounts
  (`overhead_s`) so the tier-1 overhead-bound test can prove sampling
  stays measurement, not load.
- `render_prometheus` — the `GET /metrics.prom` text exposition, rendered
  straight from the snapshot with name/kind/help looked up in
  `utils/metrics_registry.py` (counters and gauges verbatim, histograms
  as quantile-labeled summaries). One declaration point feeds JSON
  `/metrics`, the README catalog, and the Prometheus plane.
- `snap_counter`/`snap_gauge`/`snap_hist` — the shared snapshot readers
  (`sim/slo.py`, `utils/scrape.py`). The `metrics-registry` lint rule
  checks the series-name argument of these (and of the Timeline window
  queries) exactly like an emission site: an SLO bound or dashboard read
  of a never-declared series fails lint instead of reading 0 forever.

The cluster-level merge of many nodes' timelines lives in
`utils/scrape.py`; the CLI over both is `scripts/telemetry.py`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from . import metrics_registry
from .metrics import Metrics, percentile_of_sorted

Snapshot = Dict[str, Any]


# ----------------------------------------------------- snapshot readers


def snap_counter(snap: Snapshot, name: str, default: int = 0) -> int:
    """One counter out of a `Metrics.snapshot()` document."""
    return int(snap.get("counters", {}).get(name, default))


def snap_gauge(snap: Snapshot, name: str, default: float = 0.0) -> float:
    """One gauge out of a `Metrics.snapshot()` document."""
    return float(snap.get("gauges", {}).get(name, default))


def snap_hist(snap: Snapshot, name: str) -> Dict[str, float]:
    """One histogram percentile block ({} when the series never fired)."""
    out = snap.get("latency", {}).get(name, {})
    return dict(out) if isinstance(out, dict) else {}


# -------------------------------------------------------------- points


@dataclasses.dataclass
class TimelinePoint:
    """One sample: wall time, the interval it covers, and what changed."""

    t: float                       # wall-clock seconds (time.time())
    dt: float                      # seconds since the previous point
    deltas: Dict[str, int]         # counter increments over dt
    gauges: Dict[str, float]
    hists: Dict[str, Dict[str, float]]  # snapshot percentile blocks,
    #                                     plus "dcount": observations in dt

    def rates(self) -> Dict[str, float]:
        if self.dt <= 0:
            return {k: 0.0 for k in self.deltas}
        return {k: v / self.dt for k, v in self.deltas.items()}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "t": round(self.t, 3),
            "dt": round(self.dt, 3),
            "rates": {k: round(v, 4) for k, v in self.rates().items()},
            "gauges": {k: round(v, 6) for k, v in self.gauges.items()},
            "hists": {
                name: {k: round(float(v), 6) for k, v in block.items()}
                for name, block in self.hists.items()
            },
        }

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "TimelinePoint":
        dt = float(doc.get("dt", 0.0))
        return cls(
            t=float(doc.get("t", 0.0)),
            dt=dt,
            deltas={k: int(round(float(v) * dt))
                    for k, v in doc.get("rates", {}).items()},
            gauges={k: float(v) for k, v in doc.get("gauges", {}).items()},
            hists={name: {k: float(v) for k, v in block.items()}
                   for name, block in doc.get("hists", {}).items()},
        )


class Timeline:
    """Bounded in-process time series over `Metrics.snapshot()` documents.

    Thread-safe: the sampler appends from its own thread while admin
    handlers and the SLO engine query concurrently.
    """

    def __init__(self, max_points: int = 600, max_events: int = 256):
        self._lock = threading.Lock()
        self._points: Deque[TimelinePoint] = deque(  # guarded-by: _lock
            maxlen=max_points
        )
        self._events: Deque[Dict[str, Any]] = deque(  # guarded-by: _lock
            maxlen=max_events
        )
        self._prev_t: Optional[float] = None          # guarded-by: _lock
        self._prev_counters: Dict[str, int] = {}      # guarded-by: _lock
        self._prev_hist_counts: Dict[str, int] = {}   # guarded-by: _lock

    # ------------------------------------------------------------- write

    def append(self, snapshot: Snapshot,
               t: Optional[float] = None) -> TimelinePoint:
        """Fold one cumulative snapshot into the ring.

        Counter deltas are reset-aware: a value below the previous sample
        (process restart wiped the counter) contributes its whole new
        value as the delta — the Prometheus rate() convention — so a
        rolling restart reads as a blip, not a negative rate. The FIRST
        sample only seeds baselines (every delta is 0): the process may
        have been running long before the timeline started, and its
        boot-era totals must not read as a rate spike in the first
        window (the two-samples-for-a-rate rule)."""
        now = time.time() if t is None else t
        counters = {k: int(v)
                    for k, v in snapshot.get("counters", {}).items()}
        hists_in = snapshot.get("latency", {})
        with self._lock:
            first = self._prev_t is None
            dt = 0.0 if first else now - self._prev_t
            deltas: Dict[str, int] = {}
            for name, cur in counters.items():
                prev = self._prev_counters.get(name, 0)
                deltas[name] = (0 if first
                                else cur - prev if cur >= prev else cur)
            hists: Dict[str, Dict[str, float]] = {}
            for name, block in hists_in.items():
                if not isinstance(block, dict):
                    continue
                out = {k: float(v) for k, v in block.items()}
                cur_n = int(block.get("count", 0))
                prev_n = self._prev_hist_counts.get(name, 0)
                out["dcount"] = float(
                    0 if first
                    else cur_n - prev_n if cur_n >= prev_n else cur_n
                )
                self._prev_hist_counts[name] = cur_n
                hists[name] = out
            point = TimelinePoint(
                t=now, dt=max(0.0, dt), deltas=deltas,
                gauges={k: float(v)
                        for k, v in snapshot.get("gauges", {}).items()},
                hists=hists,
            )
            self._prev_t = now
            self._prev_counters = counters
            self._points.append(point)
            return point

    def record_event(self, kind: str, detail: str = "",
                     t: Optional[float] = None,
                     **attrs: Any) -> Dict[str, Any]:
        """Annotate the timeline (alert raised/cleared, fault phase...)."""
        event: Dict[str, Any] = {
            "t": round(time.time() if t is None else t, 3),
            "kind": kind, "detail": detail,
        }
        event.update(attrs)
        with self._lock:
            self._events.append(event)
        return event

    # ------------------------------------------------------------ queries

    def points(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[TimelinePoint]:
        with self._lock:
            pts = list(self._points)
        if window_s is None:
            return pts
        cutoff = (time.time() if now is None else now) - window_s
        return [p for p in pts if p.t >= cutoff]

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def counter_delta(self, name: str, window_s: float,
                      now: Optional[float] = None) -> int:
        """Counter increments observed inside the last `window_s`."""
        return sum(p.deltas.get(name, 0)
                   for p in self.points(window_s, now))

    def counter_rate(self, name: str, window_s: float,
                     now: Optional[float] = None) -> Optional[float]:
        """Mean increments/second over the window; None when the window
        holds no samples (unknown, as opposed to a measured zero)."""
        pts = self.points(window_s, now)
        span = sum(p.dt for p in pts)
        if span <= 0:
            return None
        return sum(p.deltas.get(name, 0) for p in pts) / span

    def hist_rate(self, name: str, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """Histogram observations/second over the window (from the
        per-point `dcount` deltas), None when the window is empty."""
        pts = self.points(window_s, now)
        span = sum(p.dt for p in pts)
        if span <= 0:
            return None
        return sum(p.hists.get(name, {}).get("dcount", 0.0)
                   for p in pts) / span

    def gauge_last(self, name: str) -> Optional[float]:
        with self._lock:
            for p in reversed(self._points):
                if name in p.gauges:
                    return p.gauges[name]
        return None

    def gauge_percentile(self, name: str, window_s: float, p: float,
                         now: Optional[float] = None) -> Optional[float]:
        """Percentile of a gauge's sampled values over the window (e.g.
        p95 queue depth), via the shared nearest-rank helper."""
        vals = sorted(
            pt.gauges[name] for pt in self.points(window_s, now)
            if name in pt.gauges
        )
        if not vals:
            return None
        return percentile_of_sorted(vals, p)

    def hist_p95(self, name: str, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
        """Worst p95 reported for the series inside the window. The
        underlying reservoir is cumulative, so this bounds the window's
        true p95 from above — conservative for alerting; use
        `LatencyHistogram.window_percentile` for the exact sliding-window
        quantile on in-process series."""
        vals = [
            p.hists[name]["p95_s"] for p in self.points(window_s, now)
            if "p95_s" in p.hists.get(name, {})
        ]
        return max(vals) if vals else None

    # ------------------------------------------------------------- export

    def to_dict(self) -> Dict[str, Any]:
        return {
            "points": [p.to_dict() for p in self.points()],
            "events": self.events(),
        }

    @classmethod
    def from_dict(cls, doc: Dict[str, Any],
                  max_points: int = 100000) -> "Timeline":
        """Rehydrate an exported timeline (the capacity fitter's input)."""
        tl = cls(max_points=max_points)
        with tl._lock:
            for pdoc in doc.get("points", []):
                point = TimelinePoint.from_dict(pdoc)
                tl._points.append(point)
                tl._prev_t = point.t
            for event in doc.get("events", []):
                tl._events.append(dict(event))
        return tl


class TimelineSampler:
    """Daemon thread: `metrics.snapshot()` -> `timeline` every interval.

    Self-accounting: `samples` and `overhead_s` (wall time spent inside
    snapshot+append) let the tier-1 test bound the sampler's cost — the
    watcher must stay ~free relative to what it watches."""

    def __init__(self, metrics: Metrics, interval_s: float = 1.0,
                 max_points: int = 600,
                 timeline: Optional[Timeline] = None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.metrics = metrics
        self.interval_s = interval_s
        self.timeline = timeline if timeline is not None \
            else Timeline(max_points=max_points)
        self.samples = 0        # written by the sampler thread only
        self.overhead_s = 0.0   # written by the sampler thread only
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TimelineSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="timeline-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            t0 = time.perf_counter()
            try:
                self.timeline.append(self.metrics.snapshot())
            except Exception:  # pragma: no cover - keep sampling
                pass
            self.samples += 1
            self.overhead_s += time.perf_counter() - t0


# ------------------------------------------------------- shared formulas


def degraded_rate_burn(timeline: Timeline, window_s: float, bound: float,
                       now: Optional[float] = None) -> Optional[float]:
    """Degraded-answer burn over one window of a (cluster) timeline:
    (degraded / gate-eligible requests) / bound. THE formula — the
    continuous SLO engine's alerting (sim/slo.py) and the live dashboard
    (scripts/telemetry.py) share it, so an operator watching burn
    figures sees the same number that pages. Gate-rejected asks never
    reach the tutoring decision and can't degrade, so they are excluded
    from the denominator — leaving them in would dilute a total blackout
    to a sub-threshold ratio. Without a gate the correction is zero and
    the ratio is deg/req. None = the window holds no samples (no
    evidence, not a zero)."""
    req = timeline.counter_rate(metrics_registry.LLM_REQUESTS, window_s,
                                now)
    deg = timeline.counter_rate(metrics_registry.TUTORING_DEGRADED,
                                window_s, now)
    if req is None or deg is None:
        return None
    rejected = timeline.counter_rate(metrics_registry.GATE_REJECT,
                                     window_s, now) or 0.0
    denom = max(req - rejected, deg)
    if denom <= 0:
        return 0.0
    return (deg / denom) / bound


# -------------------------------------------------- Prometheus exposition


def _prom_escape(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_value(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return format(value, ".9g")


def _prom_header(lines: List[str], name: str, kind: str) -> None:
    if metrics_registry.is_declared(name):
        spec = metrics_registry.spec(name)
        lines.append(f"# HELP {name} {_prom_escape(spec.help)}")
        # The registry's "histogram" is a percentile reservoir; its
        # exposition (quantile-labeled samples + _count/_sum) is what
        # Prometheus calls a summary.
        out_kind = ("summary" if spec.kind == metrics_registry.HISTOGRAM
                    else spec.kind)
        lines.append(f"# TYPE {name} {out_kind}")
    else:
        # Ad-hoc series (tests, scratch code) still export, typed by the
        # snapshot section they came from; only registry-declared names
        # carry HELP (and only those pass the metrics-registry lint).
        lines.append(f"# TYPE {name} {kind}")


def render_prometheus(snapshot: Snapshot) -> str:
    """Prometheus text exposition (0.0.4) of one Metrics snapshot.

    Counters and gauges render verbatim; histograms render as summaries
    (quantile-labeled gauges from the reservoir percentiles, plus
    `_count` and `_sum`), matching what the JSON `/metrics` document
    already reports so the two planes cannot disagree."""
    lines: List[str] = []
    counters = snapshot.get("counters", {})
    for name in sorted(counters):
        _prom_header(lines, name, metrics_registry.COUNTER)
        lines.append(f"{name} {_prom_value(float(counters[name]))}")
    gauges = snapshot.get("gauges", {})
    for name in sorted(gauges):
        _prom_header(lines, name, metrics_registry.GAUGE)
        lines.append(f"{name} {_prom_value(float(gauges[name]))}")
    hists = snapshot.get("latency", {})
    for name in sorted(hists):
        block = hists[name]
        if not isinstance(block, dict):
            continue
        _prom_header(lines, name, "summary")
        for q, key in (("0.5", "p50_s"), ("0.9", "p90_s"),
                       ("0.95", "p95_s"), ("0.99", "p99_s")):
            if key in block:
                lines.append(
                    f'{name}{{quantile="{q}"}} '
                    f"{_prom_value(float(block[key]))}"
                )
        count = float(block.get("count", 0))
        mean = float(block.get("mean_s", 0.0))
        lines.append(f"{name}_count {_prom_value(count)}")
        lines.append(f"{name}_sum {_prom_value(mean * count)}")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------- admin-plane glue


def timeline_admin_get(path: str,
                       timeline: Optional[Timeline]) -> Dict[str, Any]:
    """`GET /admin/timeline` handler body, shared by both servers and the
    sim cluster: the node's full ring + events as one JSON document."""
    if path != "/admin/timeline":
        raise KeyError(path)
    if timeline is None:
        raise ValueError("telemetry timeline is disabled on this node")
    return {"ok": True, "timeline": timeline.to_dict()}
