"""Forwarding auth for the tutoring port.

The reference's tutoring server answers anyone who reaches the port —
`request.token` is never read (reference: GUI_RAFT_LLM_SourceCode/
tutoring_server.py:33-37), so the LMS session check and the BERT relevance
gate can be bypassed by dialing the tutoring node directly.

Fix: the LMS leader and the tutoring node share a secret; the leader stamps
each forwarded query with an HMAC ticket carried in the existing
`QueryRequest.token` field (the wire contract is unchanged — the field is a
string either way). The tutoring node only answers queries whose ticket
verifies. Clients never see the secret; the student's session token is
validated on the LMS before forwarding, exactly as before.
"""

from __future__ import annotations

import hashlib
import hmac
import time

# Tickets expire: traffic is plaintext gRPC, so an observed ticket must not
# grant indefinite replay access to the tutoring port. 60 s comfortably
# covers leader→tutoring forwarding latency.
TICKET_TTL_S = 60


def _mac(key: str, expires_at: int, query: str) -> str:
    msg = f"{expires_at}|{query}".encode()
    return hmac.new(key.encode(), msg, hashlib.sha256).hexdigest()


def sign_query(key: str, query: str, now: float | None = None) -> str:
    """Expiring ticket the LMS leader attaches to a gate-approved query.

    Format "<unix-expiry>:<hmac-sha256 of 'expiry|query'>" — the expiry is
    authenticated, so it can't be extended by the bearer.
    """
    expires_at = int(now if now is not None else time.time()) + TICKET_TTL_S
    return f"{expires_at}:{_mac(key, expires_at, query)}"


def verify_query(key: str, query: str, ticket: str,
                 now: float | None = None) -> bool:
    expiry_s, sep, mac = (ticket or "").partition(":")
    if not sep or not expiry_s.isdigit():
        return False
    expires_at = int(expiry_s)
    if (now if now is not None else time.time()) >= expires_at:
        return False
    return hmac.compare_digest(_mac(key, expires_at, query), mac)
