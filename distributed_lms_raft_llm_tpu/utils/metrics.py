"""Lightweight serving metrics: counters + latency histograms.

The reference has no observability beyond ~80 print() call sites
(SURVEY.md §5). The BASELINE north-star metric is p50 TTFT per student
query, so latency percentiles are first-class here: every RPC entry point
records into a histogram, and servers log/export snapshots.

Thread-safe, dependency-free; values are plain floats so snapshots can be
JSON-serialized straight into logs or the bench harness.
"""

from __future__ import annotations

import bisect
import math
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .locks import make_lock


def percentile_of_sorted(samples: Sequence[float], p: float) -> float:
    """Nearest-rank percentile of an ascending-sorted sequence: the
    smallest sample ranked at or above p% of the distribution.

    The ONE quantile index formula in the repo. `LatencyHistogram`
    (percentile + snapshot), `sim/slo.stage_breakdown`, and the telemetry
    timeline's windowed percentiles all share it, so small-n behavior
    agrees everywhere: p50 of a 2-sample set is the FIRST sample
    (ceil(0.5*2)-1 == 0), not the max — the old per-call-site `n // 2` /
    `int(n * p / 100)` formulas disagreed exactly there.
    """
    n = len(samples)
    if n == 0:
        raise ValueError("percentile of an empty sequence")
    idx = min(n - 1, max(0, math.ceil(n * p / 100.0) - 1))
    return samples[idx]


class LatencyHistogram:
    """Reservoir of recent latencies with percentile queries.

    Alongside the centered reservoir (all-time percentiles), a small
    time-stamped ring of the most recent observations backs
    `window_percentile` — the true sliding-window quantile the continuous
    SLO engine (sim/slo.py) evaluates burn rates against, which a
    cumulative reservoir cannot answer (an early spike would hold the
    all-time p95 up forever).
    """

    def __init__(self, max_samples: int = 4096, recent: int = 1024):
        self._samples: List[float] = []  # guarded-by: _lock
        self._max = max_samples
        self._count = 0                  # guarded-by: _lock
        self._total = 0.0                # guarded-by: _lock
        # (monotonic time, value) of the newest observations, for
        # windowed quantiles; bounded so observe() stays O(log n).
        self._recent: Deque[Tuple[float, float]] = deque(  # guarded-by: _lock
            maxlen=recent
        )
        self._lock = make_lock("LatencyHistogram._lock")

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._recent.append((time.monotonic(), seconds))
            bisect.insort(self._samples, seconds)
            if len(self._samples) > self._max:
                # Drop alternating extremes to keep the reservoir centered.
                self._samples.pop(0 if self._count % 2 else -1)

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            return percentile_of_sorted(self._samples, p)

    def window_percentile(self, window_s: float, p: float,
                          now: Optional[float] = None) -> Optional[float]:
        """Percentile of the observations from the last `window_s`
        seconds (None when the window is empty — distinct from 0.0).
        Bounded by the recent ring: under extreme rates the window may
        cover fewer observations than arrived, never more."""
        cutoff = (now if now is not None else time.monotonic()) - window_s
        with self._lock:
            vals = sorted(v for t, v in self._recent if t >= cutoff)
        if not vals:
            return None
        return percentile_of_sorted(vals, p)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = len(self._samples)
            if n == 0:
                return {"count": 0, "samples": 0}
            return {
                "count": self._count,
                # Reservoir size the percentiles below are computed from
                # (== count until the reservoir wraps at max_samples):
                # readers can judge how trustworthy a p95/p99 is.
                "samples": n,
                "mean_s": self._total / self._count,
                "p50_s": percentile_of_sorted(self._samples, 50),
                "p90_s": percentile_of_sorted(self._samples, 90),
                # p95 is the SLO percentile the semester simulator (sim/)
                # asserts from /metrics, so it ships in every snapshot.
                "p95_s": percentile_of_sorted(self._samples, 95),
                "p99_s": percentile_of_sorted(self._samples, 99),
                "max_s": self._samples[-1],
            }


class Metrics:
    """Named counters + histograms + gauges; one per server process."""

    def __init__(self):
        self._counters: Dict[str, int] = {}          # guarded-by: _lock
        self._hists: Dict[str, LatencyHistogram] = {}  # guarded-by: _lock
        self._gauges: Dict[str, float] = {}          # guarded-by: _lock
        # Named for the live acquisition-order graph (utils/locks.py).
        self._lock = make_lock("Metrics._lock")

    def set_gauge(self, name: str, value: float) -> None:
        """Last-value gauge for dimensionless readings (ratios, sizes) —
        NOT latencies: histogram snapshots are rendered with seconds
        suffixes, so a unitless value there reads as a bogus latency."""
        with self._lock:
            self._gauges[name] = float(value)

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def hist(self, name: str) -> LatencyHistogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = LatencyHistogram()
            return self._hists[name]

    def time(self, name: str) -> "_Timer":
        return _Timer(self.hist(name))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counters = dict(self._counters)
            hists = {k: h.snapshot() for k, h in self._hists.items()}
            gauges = dict(self._gauges)
        out = {"counters": counters, "latency": hists}
        if gauges:
            out["gauges"] = gauges
        return out


class _Timer:
    def __init__(self, hist: LatencyHistogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False
