"""Lightweight serving metrics: counters + latency histograms.

The reference has no observability beyond ~80 print() call sites
(SURVEY.md §5). The BASELINE north-star metric is p50 TTFT per student
query, so latency percentiles are first-class here: every RPC entry point
records into a histogram, and servers log/export snapshots.

Thread-safe, dependency-free; values are plain floats so snapshots can be
JSON-serialized straight into logs or the bench harness.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional


class LatencyHistogram:
    """Reservoir of recent latencies with percentile queries."""

    def __init__(self, max_samples: int = 4096):
        self._samples: List[float] = []  # guarded-by: _lock
        self._max = max_samples
        self._count = 0                  # guarded-by: _lock
        self._total = 0.0                # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            bisect.insort(self._samples, seconds)
            if len(self._samples) > self._max:
                # Drop alternating extremes to keep the reservoir centered.
                self._samples.pop(0 if self._count % 2 else -1)

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            idx = min(int(len(self._samples) * p / 100.0), len(self._samples) - 1)
            return self._samples[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = len(self._samples)
            if n == 0:
                return {"count": 0, "samples": 0}
            return {
                "count": self._count,
                # Reservoir size the percentiles below are computed from
                # (== count until the reservoir wraps at max_samples):
                # readers can judge how trustworthy a p95/p99 is.
                "samples": n,
                "mean_s": self._total / self._count,
                "p50_s": self._samples[n // 2],
                "p90_s": self._samples[min(int(n * 0.9), n - 1)],
                # p95 is the SLO percentile the semester simulator (sim/)
                # asserts from /metrics, so it ships in every snapshot.
                "p95_s": self._samples[min(int(n * 0.95), n - 1)],
                "p99_s": self._samples[min(int(n * 0.99), n - 1)],
                "max_s": self._samples[-1],
            }


class Metrics:
    """Named counters + histograms + gauges; one per server process."""

    def __init__(self):
        self._counters: Dict[str, int] = {}          # guarded-by: _lock
        self._hists: Dict[str, LatencyHistogram] = {}  # guarded-by: _lock
        self._gauges: Dict[str, float] = {}          # guarded-by: _lock
        self._lock = threading.Lock()

    def set_gauge(self, name: str, value: float) -> None:
        """Last-value gauge for dimensionless readings (ratios, sizes) —
        NOT latencies: histogram snapshots are rendered with seconds
        suffixes, so a unitless value there reads as a bogus latency."""
        with self._lock:
            self._gauges[name] = float(value)

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def hist(self, name: str) -> LatencyHistogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = LatencyHistogram()
            return self._hists[name]

    def time(self, name: str) -> "_Timer":
        return _Timer(self.hist(name))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counters = dict(self._counters)
            hists = {k: h.snapshot() for k, h in self._hists.items()}
            gauges = dict(self._gauges)
        out = {"counters": counters, "latency": hists}
        if gauges:
            out["gauges"] = gauges
        return out


class _Timer:
    def __init__(self, hist: LatencyHistogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False
