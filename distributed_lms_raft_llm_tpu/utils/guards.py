"""Runtime guards: the dynamic counterparts of the static lint rules.

`scripts/lint.py` catches dispatch-hygiene and asyncio-discipline bugs that
are visible in source; this module catches the ones that only exist at
runtime, with the SAME vocabulary so the two halves reinforce each other:

- `intended_transfer()` marks a sanctioned host<->device sync point. The
  static rule `no-host-sync-in-dispatch` accepts syncs inside this block,
  and under strict dispatch the jax transfer guard allows them — one
  marker serves both checkers.
- `strict_dispatch()` / `enable_strict_dispatch()` turn on
  `jax.transfer_guard_device_to_host("disallow")`: any device->host
  readback OUTSIDE an `intended_transfer()` block raises on backends that
  move bytes (TPU/GPU; the CPU backend's readbacks are zero-copy and never
  trip the guard — the static rule is the enforcement there). Exposed as
  the tutoring server's `--strict-dispatch` flag.
- `compile_count_guard(...)` generalizes PR 2's compile-count assertion:
  a context manager over jitted callables that raises `RecompileError`
  when the guarded region compiled more programs than allowed — the
  silent-recompile-per-request failure mode (`P()` vs `P(None, None)`)
  made mechanical. `compile_count_guard(expected_from_inventory(engine))`
  additionally cross-validates against the static program manifest
  (`engine/program_inventory.py`): at exit every inventoried program's
  cache size must EQUAL the manifest's expectation — more means warmup
  missed a program, fewer means the checked-in inventory is stale, and
  both directions raise.
- `LoopWatchdog` measures asyncio loop stalls: the Raft tick loop reports
  its scheduling lag here; lag lands in a Metrics histogram (exported via
  /metrics as `<name>_lag`) and stalls above the threshold warn and count
  (`<name>_stalls`). The static rule `no-blocking-in-async` prevents the
  common causes; the watchdog catches whatever slips through.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

log = logging.getLogger(__name__)


class RecompileError(AssertionError):
    """A guarded region compiled programs it promised not to (the warmup
    didn't cover a live code path — the PR-2 bug class)."""


class InventoryMismatchError(RecompileError):
    """The runtime program caches and the static manifest
    (engine/program_inventory.py) disagree — an uncovered program, a stale
    inventory entry, or drifted domain math. Regenerate with
    `python scripts/gen_program_inventory.py --write` if the change was
    intentional."""


# --------------------------------------------------------- transfer guards


# One-time flag: strict dispatch on a CPU backend warns exactly once per
# process (tests reset it to re-pin the warning).
_warned_cpu_noop = False


def _warn_if_cpu_noop() -> None:
    """The jax transfer guard only fires on backends where device->host
    readbacks move bytes; the CPU backend's readbacks are zero-copy and
    NEVER trip it, so `--strict-dispatch` on CPU would silently enforce
    nothing. Say so once — and point at the static rule
    (`no-host-sync-in-dispatch`) that IS the CPU-side enforcement."""
    global _warned_cpu_noop
    if _warned_cpu_noop:
        return
    import jax

    if jax.default_backend() == "cpu":
        _warned_cpu_noop = True
        log.warning(
            "strict dispatch: the jax transfer guard is a no-op on the CPU "
            "backend (readbacks are zero-copy) — unmarked syncs will NOT "
            "raise here; the `no-host-sync-in-dispatch` lint rule is the "
            "enforcement on CPU (see README: dlrl-lint)"
        )


@contextlib.contextmanager
def intended_transfer() -> Iterator[None]:
    """Mark a sanctioned host<->device sync point.

    Inside this block, device readbacks are allowed even under strict
    dispatch. The static rule `no-host-sync-in-dispatch` recognizes the
    same block lexically, so every sync in a dispatch module is either
    wrapped here (auditable, greppable) or a lint finding.
    """
    import jax

    with jax.transfer_guard_device_to_host("allow"):
        yield


@contextlib.contextmanager
def strict_dispatch() -> Iterator[None]:
    """Scoped strict mode: device->host readbacks outside
    `intended_transfer()` raise (on backends where readbacks are real
    transfers; on CPU this is a documented no-op — a one-time warning
    points at the lint rule that enforces there). Engine test fixtures
    wrap hot-path runs in this."""
    import jax

    _warn_if_cpu_noop()
    with jax.transfer_guard_device_to_host("disallow"):
        yield


def enable_strict_dispatch() -> None:
    """Process-wide strict mode (the `--strict-dispatch` server flag):
    every unmarked device->host readback from here on raises. Warmup and
    serving share the setting, so a sync the warmup path tolerates cannot
    hide in the live path."""
    import jax

    _warn_if_cpu_noop()
    jax.config.update("jax_transfer_guard_device_to_host", "disallow")
    log.info("strict dispatch: unmarked device->host transfers will raise")


# ------------------------------------------------------ compile-count guard


class _CompileCounts:
    """Snapshot of per-callable jit cache sizes."""

    def __init__(self, fns: Sequence[object]):
        self.fns = list(fns)
        self.baseline = [self._size(f) for f in self.fns]

    @staticmethod
    def _size(fn: object) -> int:
        size = getattr(fn, "_cache_size", None)
        if size is None:
            raise TypeError(
                f"{fn!r} is not a jitted callable (no _cache_size); pass "
                "the jax.jit result itself"
            )
        return int(size())

    def new_compiles(self) -> int:
        return sum(
            self._size(f) - b for f, b in zip(self.fns, self.baseline)
        )


class InventoryExpectation:
    """Absolute expected program-cache sizes for an engine's inventoried
    (warmup-covered) programs, from the static manifest. Built via
    `expected_from_inventory(engine)`; consumed by `compile_count_guard`.
    """

    def __init__(self, engine: object):
        from ..engine import program_inventory as _inv

        self.engine = engine
        self.expected = _inv.expected_counts(engine)  # attr -> size
        self.fns = {
            attr: getattr(engine, attr) for attr in sorted(self.expected)
        }

    def mismatches(self) -> Dict[str, Tuple[int, int]]:
        """{attr: (actual, expected)} for every program whose live cache
        size differs from the manifest expectation, in either direction."""
        out: Dict[str, Tuple[int, int]] = {}
        for attr, fn in self.fns.items():
            actual = _CompileCounts._size(fn)
            if actual != self.expected[attr]:
                out[attr] = (actual, self.expected[attr])
        return out


def expected_from_inventory(engine: object) -> InventoryExpectation:
    """The static<->runtime cross-validation mode of `compile_count_guard`:

        eng.warmup()
        with compile_count_guard(expected_from_inventory(eng)):
            ... live serving ...

    The region must compile nothing new (the classic warmup-coverage
    claim), AND at exit every program named by engine/program_inventory.py
    must hold EXACTLY the manifest's expected count — more means an
    uncovered program slipped through, fewer means the checked-in
    inventory overstates the domain (stale manifest). Either direction
    raises InventoryMismatchError.
    """
    return InventoryExpectation(engine)


@contextlib.contextmanager
def compile_count_guard(
    *fns: object, allow: int = 0, what: str = "guarded region"
) -> Iterator[_CompileCounts]:
    """Assert the region compiles at most `allow` new programs across the
    given jitted callables.

    Generalizes the PR-2 warmup-coverage guard: wrap the live serving path
    after warmup with `allow=0` and any program the warmup failed to cover
    — a spelling-different sharding, an unexpected shape — raises
    `RecompileError` at the moment it happens instead of shipping as a
    silent tens-of-seconds stall per request.

        with compile_count_guard(eng._step, eng._install) as guard:
            eng.drain()
        # guard.new_compiles() also available for reporting

    Passing `expected_from_inventory(engine)` as the sole argument guards
    the engine's whole inventoried program set and additionally asserts
    the post-region cache sizes EQUAL the static manifest's expectations
    (see expected_from_inventory).
    """
    expectation: Optional[InventoryExpectation] = None
    if len(fns) == 1 and isinstance(fns[0], InventoryExpectation):
        expectation = fns[0]
        fns = tuple(expectation.fns.values())
        what = (
            f"{type(expectation.engine).__name__} inventoried program set"
            if what == "guarded region" else what
        )
    counts = _CompileCounts(fns)
    yield counts
    new = counts.new_compiles()
    if new > allow:
        raise RecompileError(
            f"{what} compiled {new} new program(s) (allowed {allow}): "
            "warmup does not cover a live code path — check for "
            "spelling-different shardings or unexpected shapes"
        )
    if expectation is not None:
        bad = expectation.mismatches()
        if bad:
            detail = ", ".join(
                f"{attr}: {actual} compiled vs {exp} inventoried"
                for attr, (actual, exp) in sorted(bad.items())
            )
            raise InventoryMismatchError(
                f"{what} disagrees with engine/program_inventory.py "
                f"({detail}) — more than inventoried means warmup missed a "
                "program; fewer means the manifest is stale "
                "(scripts/gen_program_inventory.py --write)"
            )


# ---------------------------------------------------------- loop watchdog


class LoopWatchdog:
    """Event-loop stall detector for a periodic asyncio task.

    The owner of a loop (the Raft tick loop) calls `observe(lag_s)` with
    how late each iteration ran versus its schedule; lag lands in a
    Metrics histogram (`<name>_lag`, seconds — /metrics renders latency
    percentiles) and stalls above `warn_above_s` increment the
    `<name>_stalls` counter and log a rate-limited warning. A stalled loop
    means SOMETHING blocked the thread — sync IO, a device readback, a
    long pure-Python apply — exactly what `raft/core.py`'s "nothing to
    lock" single-task design must never experience.

    For loops the caller does not own, `run()` is a standalone heartbeat
    coroutine: it sleeps `interval_s` and observes its own wake-up lag.
    """

    def __init__(
        self,
        metrics: Optional[Any] = None,
        *,
        name: str = "loop",
        warn_above_s: float = 0.25,
        warn_every_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        lag_metric: Optional[str] = None,
        stalls_metric: Optional[str] = None,
    ):
        self.metrics = metrics
        self.name = name
        self.warn_above_s = warn_above_s
        self.warn_every_s = warn_every_s
        self._clock = clock
        self._last_warn = 0.0
        self.max_lag_s = 0.0
        self.stalls = 0
        # Series names default to `<name>_lag`/`<name>_stalls`; wiring
        # sites that export to /metrics pin them from the metrics
        # registry instead (make_tick_watchdog), so the emitted names
        # stay declared.
        self.lag_metric = lag_metric or f"{name}_lag"
        self.stalls_metric = stalls_metric or f"{name}_stalls"

    def observe(self, lag_s: float) -> None:
        lag_s = max(0.0, float(lag_s))
        self.max_lag_s = max(self.max_lag_s, lag_s)
        if self.metrics is not None:
            # Generic infrastructure: the name is whatever the wiring site
            # chose (registry constants for the exported loops), so the
            # static declared-name check happens there, not here.
            self.metrics.hist(self.lag_metric).observe(lag_s)  # lint: disable=metrics-registry
        if lag_s <= self.warn_above_s:
            return
        self.stalls += 1
        if self.metrics is not None:
            self.metrics.inc(self.stalls_metric)  # lint: disable=metrics-registry
        now = self._clock()
        if now - self._last_warn >= self.warn_every_s:
            self._last_warn = now
            log.warning(
                "%s stalled %.0f ms (threshold %.0f ms): something is "
                "blocking the event loop (%d stalls so far)",
                self.name, lag_s * 1e3, self.warn_above_s * 1e3, self.stalls,
            )

    async def run(self, interval_s: float = 0.1) -> None:
        """Standalone heartbeat for loops the caller can't instrument."""
        import asyncio

        while True:
            before = self._clock()
            await asyncio.sleep(interval_s)
            self.observe(self._clock() - before - interval_s)


def make_tick_watchdog(
    metrics: Optional[Any] = None, *, tick_interval: float,
    name: str = "raft_tick", stall_factor: float = 10.0,
) -> Optional[LoopWatchdog]:
    """The Raft wiring: warn when a tick lands `stall_factor` intervals
    late (a 10 ms tick loop warning at 100 ms of lag — late enough to
    matter for heartbeats, early enough to catch before elections fire).
    Returns None without metrics so callers can wire unconditionally."""
    if metrics is None:
        return None
    # Pin the default wiring's series names from the registry so
    # `raft_tick_lag`/`raft_tick_stalls` stay declared-and-live under the
    # metrics-registry rule; a custom `name` keeps the derived pair.
    from . import metrics_registry

    default = name == "raft_tick"
    return LoopWatchdog(
        metrics, name=name, warn_above_s=tick_interval * stall_factor,
        lag_metric=metrics_registry.RAFT_TICK_LAG if default else None,
        stalls_metric=metrics_registry.RAFT_TICK_STALLS if default else None,
    )


def make_serving_watchdog(
    metrics: Any, *, warn_above_s: float = 0.25,
) -> LoopWatchdog:
    """`make_tick_watchdog` generalized to the gRPC serving event loop:
    the server entry points run `watchdog.run(interval)` as a standalone
    heartbeat task, so a handler that blocks the loop (sync IO, a device
    readback, a long pure-Python stretch) shows up as the
    `serving_tick_lag` histogram and `serving_tick_stalls` counter in
    /metrics instead of being inferred from p99 latency tails. Every
    server entrypoint owns a Metrics instance, so `metrics` is required —
    callers chain `.run()` directly."""
    from . import metrics_registry

    return LoopWatchdog(
        metrics, name="serving_tick", warn_above_s=warn_above_s,
        lag_metric=metrics_registry.SERVING_TICK_LAG,
        stalls_metric=metrics_registry.SERVING_TICK_STALLS,
    )
