"""Injectable filesystem seam + disk fault injection for the storage layer.

PRs 1-4 hardened the *network* fault surface (drops, delays, partitions,
chaos soaks); the *disk* surface was untested — and the storage modules
called `open`/`os.fsync`/`os.replace` directly, so no test could interpose
on them. This module is the seam: `raft/storage.py`, `lms/persistence.py`,
and the blob store route every byte they persist through a `FileSystem`
object, and three implementations plug in:

- `FileSystem` — the real thing (`REAL_FS` module default). Adds the two
  primitives POSIX durability actually requires beyond what the stdlib
  hands out: `fsync(f)` and `fsync_dir(path)` (rename/create durability
  needs the *parent directory* synced — the ALICE/OSDI'14 bug class).
- `FaultyFS` — wraps any FileSystem with a `DiskFaultInjector`: seeded
  ENOSPC short writes, fsync failures, bit flips on written data, and
  crash-at-op-N. Wired to the live admin plane as `POST /admin/faults`
  target `"disk"`, mirroring how `FaultyTransport` shapes the network.
- `MemCrashFS` — a purely in-memory filesystem with an explicit
  durable/pending split, for the exhaustive crash-point checker
  (tests/test_crashpoints.py). Data `write()`s and namespace ops
  (create/rename/unlink) are *pending* until `fsync`/`fsync_dir`; a
  simulated crash at any op boundary then materializes a post-crash view
  under an adversarial persistence mode:

      "none"  — nothing un-fsynced survived (strict ordering),
      "all"   — everything issued survived (write-back cache flushed),
      "meta"  — namespace ops survived but un-fsynced data did not (the
                rename-beats-content reordering that turns an uploaded
                PDF into an empty file),
      ("tail", n) — like "all" but the final un-fsynced data write only
                persisted its first n bytes (n < 0 counts back from its
                end: -1 = everything but the final byte — for a WAL
                append, a complete record missing only its newline).

Determinism: `FaultyFS` samples from one `random.Random(seed)`, like
`utils.faults.FaultInjector`; a soak failure replays from its seed.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import random
import tempfile
import threading
from typing import Dict, List, Optional, Tuple, Union


class SimulatedCrash(BaseException):
    """Raised by a crash-injecting FS at the configured op index.

    Deliberately a BaseException: storage code must NOT be able to catch
    it with `except Exception` cleanup paths — a real power cut gives no
    such opportunity, and the checker asserts recovery works without it.
    """


class DiskFault(OSError):
    """An injected disk error (ENOSPC, EIO); callers treat it exactly
    like the real OSError it imitates."""


# --------------------------------------------------------------- real FS


class FileSystem:
    """The real filesystem, plus the durability primitives storage needs.

    Methods mirror the exact op set the storage modules use, so a fault
    or crash-sim implementation can interpose on every byte and every
    ordering point. File handles returned by `open`/`create_temp` are
    plain file objects (or wrappers quacking like them); all *durability*
    ops go through the seam (`fs.fsync(f)`, `fs.fsync_dir(path)`) rather
    than through the handle, which is what the durable-rename lint rule
    keys on.
    """

    def open(self, path: str, mode: str = "r",
             encoding: Optional[str] = None):
        return open(path, mode, encoding=encoding)

    def create_temp(self, dir_: str, prefix: str,
                    text: bool = False) -> Tuple[object, str]:
        """mkstemp + fdopen: an exclusive temp file in `dir_`."""
        fd, tmp = tempfile.mkstemp(dir=dir_, prefix=prefix)
        f = os.fdopen(fd, "w" if text else "wb",
                      encoding="utf-8" if text else None)
        return f, tmp

    def write(self, f, data) -> int:
        return f.write(data)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())

    def fsync_dir(self, path: str) -> None:
        """Durably persist `path`'s directory entries (created/renamed/
        unlinked names). A no-op on platforms without O_DIRECTORY opens."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform dependent
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def truncate(self, path: str, size: int) -> None:
        with open(path, "r+b") as f:
            f.truncate(size)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def remove(self, path: str) -> None:
        os.unlink(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()


REAL_FS = FileSystem()


# ------------------------------------------------------- fault injection


@dataclasses.dataclass
class DiskFaultSpec:
    """Per-op fault probabilities for the live chaos plane (all default
    to 'no fault'); mirrors utils.faults.FaultSpec for the admin API."""

    write_error: float = 0.0   # P(write raises ENOSPC after a short write)
    fsync_error: float = 0.0   # P(fsync raises EIO)
    bit_flip: float = 0.0      # P(one byte of a write is corrupted)
    crash_at_op: int = 0       # abort the process-level op stream at op N
    #                            (0 = never; used by the crash-point checker
    #                            and targeted tests, not the admin plane)

    def clamped(self) -> "DiskFaultSpec":
        return DiskFaultSpec(
            write_error=min(1.0, max(0.0, self.write_error)),
            fsync_error=min(1.0, max(0.0, self.fsync_error)),
            bit_flip=min(1.0, max(0.0, self.bit_flip)),
            crash_at_op=max(0, int(self.crash_at_op)),
        )


class DiskFaultInjector:
    """Seeded sampler for disk faults; one per node, mutable at runtime
    via `POST /admin/faults {"target": "disk", ...}` (serving/lms_server).
    Dormant (None spec, zero overhead beyond an attribute read) until the
    admin plane installs a spec."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)          # guarded-by: _lock
        self._spec: Optional[DiskFaultSpec] = None  # guarded-by: _lock
        self._ops = 0                            # guarded-by: _lock
        self._injected = 0                       # guarded-by: _lock
        self._lock = threading.Lock()

    def configure(self, **kwargs) -> DiskFaultSpec:
        known = {f.name for f in dataclasses.fields(DiskFaultSpec)}
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"unknown disk fault field(s) {sorted(bad)} "
                             f"(known: {sorted(known)})")
        spec = DiskFaultSpec(**{
            k: (int(v) if k == "crash_at_op" else float(v))
            for k, v in kwargs.items()
        }).clamped()
        with self._lock:
            self._spec = spec
        return spec

    def clear(self) -> None:
        with self._lock:
            self._spec = None

    @property
    def active(self) -> bool:
        with self._lock:
            return self._spec is not None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "injected_total": self._injected,
                "ops": self._ops,
                "spec": (dataclasses.asdict(self._spec)
                         if self._spec is not None else None),
            }

    # Sampled per FS op by FaultyFS ------------------------------------

    def on_op(self) -> None:
        """Count one durability-relevant op; crash if the spec says so."""
        with self._lock:
            self._ops += 1
            spec = self._spec
            if spec is not None and spec.crash_at_op \
                    and self._ops >= spec.crash_at_op:
                self._injected += 1
                raise SimulatedCrash(f"injected crash at disk op {self._ops}")

    def plan_write(self, nbytes: int) -> Tuple[Optional[int], Optional[int]]:
        """(short_write_len | None, flip_byte_index | None) for one write."""
        with self._lock:
            spec = self._spec
            if spec is None:
                return None, None
            short = flip = None
            if spec.write_error and self._rng.random() < spec.write_error:
                short = self._rng.randrange(nbytes + 1) if nbytes else 0
                self._injected += 1
            if spec.bit_flip and nbytes \
                    and self._rng.random() < spec.bit_flip:
                flip = self._rng.randrange(nbytes)
                self._injected += 1
            return short, flip

    def plan_fsync(self) -> bool:
        with self._lock:
            spec = self._spec
            if spec is not None and spec.fsync_error \
                    and self._rng.random() < spec.fsync_error:
                self._injected += 1
                return True
            return False


class FaultyFS(FileSystem):
    """A FileSystem with injected disk faults, mirroring FaultyTransport:
    real IO underneath, a seeded injector deciding per op whether this
    write comes up short (ENOSPC), this fsync fails (EIO), or a byte got
    flipped on its way to the platter."""

    def __init__(self, inner: FileSystem, injector: DiskFaultInjector):
        self.inner = inner
        self.injector = injector

    def open(self, path, mode="r", encoding=None):
        self.injector.on_op()
        return self.inner.open(path, mode, encoding=encoding)

    def create_temp(self, dir_, prefix, text=False):
        self.injector.on_op()
        return self.inner.create_temp(dir_, prefix, text=text)

    def write(self, f, data) -> int:
        self.injector.on_op()
        raw = data.encode("utf-8") if isinstance(data, str) else bytes(data)
        short, flip = self.injector.plan_write(len(raw))
        if flip is not None and (short is None or flip < short):
            corrupted = bytearray(raw)
            corrupted[flip] ^= 0x01
            raw = bytes(corrupted)
        if short is not None:
            partial = raw[:short]
            if partial:
                self.inner.write(
                    f, partial.decode("utf-8", errors="replace")
                    if isinstance(data, str) else partial
                )
            raise DiskFault(errno.ENOSPC, "injected ENOSPC (short write)")
        return self.inner.write(
            f, raw.decode("utf-8") if isinstance(data, str) else raw
        )

    def fsync(self, f) -> None:
        self.injector.on_op()
        if self.injector.plan_fsync():
            raise DiskFault(errno.EIO, "injected fsync failure")
        self.inner.fsync(f)

    def fsync_dir(self, path) -> None:
        self.injector.on_op()
        if self.injector.plan_fsync():
            raise DiskFault(errno.EIO, "injected dir fsync failure")
        self.inner.fsync_dir(path)

    def replace(self, src, dst) -> None:
        self.injector.on_op()
        self.inner.replace(src, dst)

    def truncate(self, path, size) -> None:
        self.injector.on_op()
        self.inner.truncate(path, size)

    # Read-side / metadata ops pass through uncounted: crashes and faults
    # land on the durability-relevant mutation stream only, keeping
    # crash-at-op-N stable across replay-time reads.
    def exists(self, path):
        return self.inner.exists(path)

    def getsize(self, path):
        return self.inner.getsize(path)

    def remove(self, path):
        self.inner.remove(path)

    def listdir(self, path):
        return self.inner.listdir(path)

    def isdir(self, path):
        return self.inner.isdir(path)

    def makedirs(self, path):
        self.inner.makedirs(path)

    def read_bytes(self, path):
        return self.inner.read_bytes(path)


# ------------------------------------------------- in-memory crash model


class _MemFile:
    """One inode: durable bytes vs the live (pending) view, plus the
    offsets of un-fsynced appends so torn tails can be enumerated."""

    def __init__(self, content: bytes = b""):
        self.content = bytearray(content)  # live view
        self.durable = bytes(content)      # as of the last fsync
        # (start, end) of each write since the last fsync, in op order.
        self.pending_writes: List[Tuple[int, int]] = []

    def clone(self) -> "_MemFile":
        f = _MemFile()
        f.content = bytearray(self.content)
        f.durable = bytes(self.durable)
        f.pending_writes = list(self.pending_writes)
        return f


class _MemHandle:
    """File-object facade over a _MemFile (append or read modes only —
    the storage layer uses nothing else)."""

    def __init__(self, fs: "MemCrashFS", path: str, mem: _MemFile,
                 mode: str):
        self._fs = fs
        self._mem = mem
        self._path = path
        self._mode = mode
        self._text = "b" not in mode
        self._pos = len(mem.content) if ("a" in mode or "w" in mode) else 0
        self.closed = False

    # Reads ------------------------------------------------------------
    def read(self, n: int = -1):
        data = bytes(self._mem.content[self._pos:])
        if n >= 0:
            data = data[:n]
        self._pos += len(data)
        return data.decode("utf-8", errors="replace") if self._text else data

    # Writes -----------------------------------------------------------
    def write(self, data) -> int:
        raw = data.encode("utf-8") if isinstance(data, str) else bytes(data)
        start = len(self._mem.content)
        self._mem.content.extend(raw)
        self._mem.pending_writes.append((start, start + len(raw)))
        self._pos = len(self._mem.content)
        return len(raw)

    def flush(self) -> None:  # flush ≠ durable; only fs.fsync persists
        pass

    def tell(self) -> int:
        return self._pos

    def truncate(self, size: int) -> None:
        del self._mem.content[size:]
        self._mem.pending_writes = [
            (s, min(e, size)) for s, e in self._mem.pending_writes if s < size
        ]
        self._pos = min(self._pos, size)

    def fileno(self) -> int:  # storage never calls os.fsync directly now
        return -1

    def close(self) -> None:
        self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


CrashMode = Union[str, Tuple[str, int]]


class MemCrashFS(FileSystem):
    """In-memory filesystem with an explicit durable/pending split.

    The live namespace (`files`) reflects every op issued; the durable
    namespace (`durable_ns`) advances only on `fsync_dir`. File *content*
    durability advances per file on `fsync`. `crash_at_op` aborts the
    op stream with SimulatedCrash; `crashed_view(mode)` then builds the
    directory state a restart would observe under the chosen adversarial
    persistence mode (see module docstring).
    """

    def __init__(self, crash_at_op: int = 0):
        self.files: Dict[str, _MemFile] = {}       # live namespace
        self.durable_ns: Dict[str, _MemFile] = {}  # as of last fsync_dir
        self.dirs: set = set()
        self.ops = 0
        self.crash_at_op = crash_at_op
        self.crashed = False
        self._tmp_seq = 0
        # Ordered log of (op_index, kind, path) for checker diagnostics.
        self.op_log: List[Tuple[int, str, str]] = []

    # -------------------------------------------------------- op stream

    def _op(self, kind: str, path: str) -> None:
        if self.crashed:
            raise SimulatedCrash("filesystem already crashed")
        self.ops += 1
        self.op_log.append((self.ops, kind, path))
        if self.crash_at_op and self.ops >= self.crash_at_op:
            self.crashed = True
            raise SimulatedCrash(f"simulated crash at op {self.ops} "
                                 f"({kind} {path})")

    @staticmethod
    def _norm(path: str) -> str:
        return os.path.normpath(os.path.abspath(path))

    # ------------------------------------------------------------- ops

    def open(self, path, mode="r", encoding=None):
        path = self._norm(path)
        writing = any(c in mode for c in "wa+")
        if writing:
            self._op("open", path)
        if path not in self.files:
            if not writing:
                raise FileNotFoundError(path)
            self.files[path] = _MemFile()
            # A newly created name is a pending namespace op: it only
            # survives a crash once its parent directory is fsynced.
        mem = self.files[path]
        if "w" in mode:
            mem.content = bytearray()
            mem.pending_writes = []
        return _MemHandle(self, path, mem, mode)

    def create_temp(self, dir_, prefix, text=False):
        dir_ = self._norm(dir_)
        self._tmp_seq += 1
        path = os.path.join(dir_, f"{prefix}{self._tmp_seq:06d}")
        self._op("create", path)
        self.files[path] = _MemFile()
        return _MemHandle(self, path, self.files[path],
                          "w" if text else "wb"), path

    def write(self, f, data) -> int:
        self._op("write", f._path)
        return f.write(data)

    def fsync(self, f) -> None:
        self._op("fsync", f._path)
        f._mem.durable = bytes(f._mem.content)
        f._mem.pending_writes = []

    def fsync_dir(self, path) -> None:
        path = self._norm(path)
        self._op("fsync_dir", path)
        # Namespace entries under `path` become durable (renames, creates,
        # unlinks); file contents stay governed by their own fsync.
        for name in list(self.durable_ns):
            if os.path.dirname(name) == path and name not in self.files:
                del self.durable_ns[name]
        for name, mem in self.files.items():
            if os.path.dirname(name) == path:
                self.durable_ns[name] = mem

    def replace(self, src, dst) -> None:
        src, dst = self._norm(src), self._norm(dst)
        self._op("rename", dst)
        if src not in self.files:
            raise FileNotFoundError(src)
        self.files[dst] = self.files.pop(src)

    def truncate(self, path, size) -> None:
        path = self._norm(path)
        self._op("truncate", path)
        mem = self.files[path]
        del mem.content[size:]
        mem.pending_writes = [
            (s, min(e, size)) for s, e in mem.pending_writes if s < size
        ]

    def exists(self, path) -> bool:
        return self._norm(path) in self.files

    def getsize(self, path) -> int:
        return len(self.files[self._norm(path)].content)

    def remove(self, path) -> None:
        path = self._norm(path)
        self._op("unlink", path)
        self.files.pop(path, None)

    def listdir(self, path) -> List[str]:
        path = self._norm(path)
        return sorted({
            os.path.relpath(name, path).split(os.sep)[0]
            for name in self.files
            if name.startswith(path + os.sep)
        } | {
            os.path.relpath(d, path).split(os.sep)[0]
            for d in self.dirs
            if d.startswith(path + os.sep)
        })

    def isdir(self, path) -> bool:
        path = self._norm(path)
        return path in self.dirs or any(
            n.startswith(path + os.sep) for n in self.files
        )

    def makedirs(self, path) -> None:
        self.dirs.add(self._norm(path))

    def read_bytes(self, path) -> bytes:
        path = self._norm(path)
        if path not in self.files:
            raise FileNotFoundError(path)
        return bytes(self.files[path].content)

    # ----------------------------------------------------- crash views

    def crashed_view(self, mode: CrashMode) -> "MemCrashFS":
        """The filesystem a restart would observe after the crash, under
        adversarial persistence `mode` ("none" | "all" | "meta" |
        ("tail", n))."""
        post = MemCrashFS()
        post.dirs = set(self.dirs)
        tail_n: Optional[int] = None
        if isinstance(mode, tuple):
            mode, tail_n = mode
        if mode == "none":
            namespace = self.durable_ns
        elif mode in ("all", "meta", "tail"):
            namespace = self.files
        else:
            raise ValueError(f"unknown crash mode {mode!r}")
        # The last pending (un-fsynced) write across all files, for "tail".
        tail_file: Optional[str] = None
        if mode == "tail":
            for op_i, kind, path in reversed(self.op_log):
                if kind == "write" and path in self.files \
                        and self.files[path].pending_writes:
                    tail_file = path
                    break
        for name, mem in namespace.items():
            if mode == "all":
                content = bytes(mem.content)
            elif mode == "meta":
                content = bytes(mem.durable)
            elif mode == "none":
                content = bytes(mem.durable)
            else:  # tail
                if name == tail_file and mem.pending_writes:
                    start, end = mem.pending_writes[-1]
                    n = tail_n if tail_n is not None else end - start
                    if n < 0:
                        n = max(0, (end - start) + n)
                    content = bytes(mem.content[:min(start + n, end)])
                else:
                    content = bytes(mem.content)
            f = _MemFile(content)
            post.files[name] = f
            post.durable_ns[name] = f
        return post
