"""The single source of truth for every metric series this repo emits.

Before this module, metric names were string literals scattered across
`lms/`, `serving/`, `engine/`, and `utils/` — a typo'd name shipped an
always-zero dashboard panel silently, and nothing said what a series
meant or whether it was a counter or a gauge. Now every series is
declared exactly once, with its kind and a help string:

    from ..utils import metrics_registry as metric
    metrics.inc(metric.TUTORING_DEGRADED)        # or the literal name —
    metrics.inc("tutoring_degraded")             # lint checks both

The `metrics-registry` lint rule (analysis/rules/metrics_registry.py)
reads THIS file's declarations as pure AST and then proves, project-wide,
that every name passed to `Metrics.inc/set_gauge/hist/time` is declared
here — undeclared literals, typos, duplicates, and undocumented series
all fail `scripts/lint.py`. Declarations must therefore stay literal
calls to `counter()`/`gauge()`/`histogram()` at module level (the rule
enforces that too). The README's metrics table is rendered from here
(`python scripts/gen_metrics_table.py --write`), so docs cannot drift
from what servers actually export.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    name: str
    kind: str
    help: str


_REGISTRY: Dict[str, MetricSpec] = {}


def _declare(kind: str, name: str, help: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"metric name {name!r} must match {_NAME_RE.pattern}")
    if not help.strip():
        raise ValueError(f"metric {name!r} needs a help string")
    if name in _REGISTRY:
        raise ValueError(f"metric {name!r} declared twice")
    _REGISTRY[name] = MetricSpec(name=name, kind=kind, help=help)
    return name


def counter(name: str, help: str) -> str:
    """Declare a monotonically increasing count; returns the name."""
    return _declare(COUNTER, name, help)


def gauge(name: str, help: str) -> str:
    """Declare a last-value reading (a ratio or size, never a latency)."""
    return _declare(GAUGE, name, help)


def histogram(name: str, help: str) -> str:
    """Declare a latency histogram (seconds; /metrics renders percentiles)."""
    return _declare(HISTOGRAM, name, help)


def all_metrics() -> List[MetricSpec]:
    """Every declared series, name-sorted (the docs/table order)."""
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def is_declared(name: str) -> bool:
    return name in _REGISTRY


def spec(name: str) -> MetricSpec:
    return _REGISTRY[name]


def render_markdown_table() -> str:
    """The README metrics catalog, one row per declared series."""
    lines = [
        "| name | kind | meaning |",
        "|---|---|---|",
    ]
    for m in all_metrics():
        lines.append(f"| `{m.name}` | {m.kind} | {m.help} |")
    return "\n".join(lines)


# =========================================================== declarations
#
# LMS service (lms/service.py) — the student-facing RPC plane.

REGISTER = counter("register", "Register RPCs received")
LOGIN = counter("login", "Login RPCs received")
POST = counter("post", "Post RPCs received (materials, assignments, queries)")
LLM_REQUESTS = counter(
    "llm_requests",
    "GetLLMAnswer RPCs received (LMS leader and tutoring node each count "
    "their own)",
)
GATE_PASS = counter(
    "gate_pass", "queries the BERT relevance gate accepted"
)
GATE_REJECT = counter(
    "gate_reject", "queries the BERT relevance gate refused"
)
LLM_TTFT = histogram(
    "llm_ttft",
    "LMS-side student-query latency: gate check + tutoring forward "
    "(the BASELINE north-star is its p50)",
)
TUTORING_DEGRADED = counter(
    "tutoring_degraded",
    "queries answered by the degraded instructor-queue fallback",
)
TUTORING_FAILURES = counter(
    "tutoring_failures", "tutoring forwards that failed (RPC error)"
)
TUTORING_DUPLICATES = counter(
    "tutoring_duplicates",
    "tutoring forwards deliberately delivered twice by the `duplicate` "
    "chaos fault",
)
TUTORING_BUDGET_EXHAUSTED = counter(
    "tutoring_budget_exhausted",
    "queries degraded because the client's remaining deadline budget was "
    "under the floor",
)
TUTORING_BREAKER_REJECTIONS = counter(
    "tutoring_breaker_rejections",
    "queries degraded because the tutoring circuit breaker was open",
)
TUTORING_BREAKER_STATE = gauge(
    "tutoring_breaker_state",
    "tutoring circuit breaker state (0 closed / 1 open / 2 half-open)",
)
TUTORING_BREAKER_CLOSED = counter(
    "tutoring_breaker_closed", "breaker transitions into CLOSED"
)
TUTORING_BREAKER_OPEN = counter(
    "tutoring_breaker_open", "breaker transitions into OPEN"
)
TUTORING_BREAKER_HALF_OPEN = counter(
    "tutoring_breaker_half_open", "breaker transitions into HALF_OPEN"
)
BLOB_FETCH_ON_MISS = counter(
    "blob_fetch_on_miss",
    "blobs healed from a peer after committed metadata referenced a "
    "locally missing file",
)
BLOB_FETCH_BUDGET_EXHAUSTED = counter(
    "blob_fetch_budget_exhausted",
    "blob fetch-on-miss sweeps skipped because the request's remaining "
    "deadline budget was under the floor (metadata-only response instead "
    "of a doomed peer sweep)",
)
REPLICATE_BUDGET_EXHAUSTED = counter(
    "replicate_budget_exhausted",
    "file-replication peers skipped because the per-upload replication "
    "budget ran out mid-sweep (anti-entropy heals them later)",
)

# Tutoring fleet router (lms/tutoring_pool.py) — cache-affinity routing,
# spill, hedging, and elastic membership across N tutoring nodes.

TUTORING_SPILLS = counter(
    "tutoring_spills",
    "tutoring forwards served by a non-affinity fleet node (the router "
    "spilled past the ring's first choice: open breaker, deep queue, "
    "insufficient budget, or the affinity node failed/was ejected)",
)
TUTORING_HEDGES = counter(
    "tutoring_hedges",
    "hedged duplicate sends issued after the affinity node sat on a "
    "forward past hedge_after_s (tail-tolerance; the loser is cancelled)",
)
TUTORING_HEDGE_WINS = counter(
    "tutoring_hedge_wins",
    "tutoring answers won by the hedged (second-choice) send — the tail "
    "latency the hedge actually shaved",
)
TUTORING_NODE_EJECTIONS = counter(
    "tutoring_node_ejections",
    "fleet members the router ejected from the ring (drain observed via "
    "/healthz or a draining refusal on the wire)",
)
TUTORING_NODE_REJOINS = counter(
    "tutoring_node_rejoins",
    "ejected fleet members re-admitted to the ring (drain ended or an "
    "operator joined them back); each rejoin starts a warm-up ramp so "
    "the node's prefix cache refills before it takes its full key share",
)
TUTORING_FLEET_SIZE = gauge(
    "tutoring_fleet_size",
    "routable tutoring fleet members (configured minus ejected/draining)",
)
STREAM_RESUMES = counter(
    "stream_resumes",
    "streamed answers resumed at the client's delivered token offset on "
    "another fleet node after the serving stream broke mid-answer (node "
    "death, open breaker, drain, or a per-chunk stall) — the "
    "resumable-stream contract's failover path; never a restart",
)
STREAM_STALLS = counter(
    "stream_stalls",
    "streamed forwards declared wedged because no chunk arrived within "
    "stream_stall_s (the stream was open but silent); each counts "
    "against the node's breaker and triggers a resume-at-offset",
)

# Breaker state -> transition counter, used by the LMS breaker observer.
# Living HERE keeps the mapping inside the declared namespace: the lint
# rule treats any name expression rooted at this module as declared by
# construction.
BREAKER_TRANSITION_COUNTERS: Dict[str, str] = {
    "closed": TUTORING_BREAKER_CLOSED,
    "open": TUTORING_BREAKER_OPEN,
    "half_open": TUTORING_BREAKER_HALF_OPEN,
}

# LMS group router (lms/group_router.py) — course-sharded control plane.
# Aggregate series only: per-group detail is deliberately served by
# GET /admin/raft instead of runtime-formatted metric names, which this
# registry forbids.

ROUTER_GROUP_FORWARDS = counter(
    "router_group_forwards",
    "LMS RPCs the router forwarded to another node because that node "
    "leads the subject's Raft group",
)
ROUTER_FANOUT_READS = counter(
    "router_fanout_reads",
    "cross-group reads (course materials, unanswered queries) fanned "
    "out to every group's leader and merged",
)
ROUTER_FROZEN_REJECTIONS = counter(
    "router_frozen_rejections",
    "writes/reads refused with UNAVAILABLE because the subject was "
    "frozen or tombstoned mid-reshard (the client retries against the "
    "flipped routing map; never a silent drop)",
)
ROUTER_UNSIGNED_METADATA = counter(
    "router_unsigned_metadata_rejections",
    "RPCs whose x-lms-* control metadata (group targeting, forced auth "
    "salt/token) carried no valid router HMAC and was ignored — a "
    "client forgery or a router-secret mismatch across the deployment",
)
RESHARD_STEPS = counter(
    "reshard_steps",
    "journaled reshard handoff steps persisted to the meta group "
    "(begin/frozen/installed/committed/done)",
)
RESHARD_COMPLETED = counter(
    "reshard_completed",
    "reshard handoffs that reached 'done': slice installed on the "
    "target, map flipped, source copy dropped behind tombstones",
)
ROUTING_MAP_VERSION = gauge(
    "routing_map_version",
    "version of the replicated course->group routing map this router "
    "last parsed from the meta group",
)

# Tutoring node (serving/tutoring_server.py + engine/batcher.py).

LLM_UNAUTHORIZED = counter(
    "llm_unauthorized",
    "direct-dial queries refused for lacking the LMS leader's HMAC ticket",
)
LLM_FAILURES = counter(
    "llm_failures", "generation failures surfaced to the client"
)
ANSWER_LATENCY = histogram(
    "answer_latency", "full GetLLMAnswer latency on the tutoring node"
)
TTFT = histogram(
    "ttft",
    "engine-measured time between a request's prefill and its first "
    "decoded token",
)
TUTORING_DRAINING = gauge(
    "tutoring_draining",
    "1 while this tutoring node is draining (POST /admin/drain): new "
    "requests are refused while in-flight work finishes and the fleet "
    "router ejects the node from its ring",
)
TUTORING_DRAIN_REJECTIONS = counter(
    "tutoring_drain_rejections",
    "requests refused because this tutoring node was draining (the "
    "router spills them to another fleet member)",
)
STREAM_CHUNKS = counter(
    "stream_chunks",
    "StreamLLMAnswer chunks sent (LMS leader and tutoring node each "
    "count their own side of the stream)",
)
SESSION_ACTIVE = gauge(
    "session_active",
    "live multi-turn tutoring sessions this node holds transcripts for "
    "([sessions] ttl_s expiry, max_sessions cap)",
)
SESSION_PINNED_BLOCKS = gauge(
    "session_pinned_blocks",
    "shared-prefix KV blocks held resident by live session pins (soft "
    "pins: TTL-expired first under eviction pressure, then "
    "soonest-expiry live pins — hard refcount pins are never evicted)",
)
SHED_EXPIRED = counter(
    "shed_expired",
    "requests dropped because their deadline budget expired before "
    "prefill dispatched",
)
SHED_OVERLOAD = counter(
    "shed_overload",
    "requests refused at admission because the bounded queue was full "
    "(RESOURCE_EXHAUSTED on the wire)",
)
ENGINE_BATCHES = counter(
    "engine_batches", "device batches dispatched by the group batcher"
)
SPEC_TOKENS_PER_WINDOW = gauge(
    "spec_tokens_per_window",
    "speculation effectiveness: mean emitted tokens per verify window "
    "(1.0 = nothing accepted, ceiling spec_tokens+1)",
)
SPEC_ACCEPTED_TOKENS = counter(
    "spec_accepted_tokens",
    "tokens speculation produced beyond the guaranteed one per verify "
    "window",
)
MEGASTEP_K = gauge(
    "megastep_k",
    "live megastep controller value: device chunks fused per host "
    "dispatch (1 = plain chunk loop; grows toward megastep_max when "
    "idle, capped at the next guaranteed slot-free horizon while "
    "admissions wait)",
)
MEGASTEP_DEAD_LANE_TOKENS = counter(
    "megastep_dead_lane_tokens",
    "pad token positions decoded by slots that finished inside a "
    "megastep before its boundary let the host reap them (spec-mode "
    "lanes count spec_tokens+1 positions each; megastep overhead, zero "
    "in chunk-loop mode)",
)
HOST_DISPATCHES_PER_TOKEN = gauge(
    "host_dispatches_per_token",
    "host program dispatches paid per emitted token on the paged engine "
    "(cumulative ratio; the megastep exists to shrink it)",
)
PREFILL_STALL_MS = counter(
    "prefill_stall_ms",
    "host wall milliseconds the paged decode train spent blocked on "
    "sequential admission (prefill dispatches + the first-token sync "
    "while live slots waited); 0 by construction under fused staged "
    "admission (prefill_chunk_tokens > 0)",
)
DECODE_STALLED_TOKENS = counter(
    "decode_stalled_tokens",
    "proxy decode tokens the live slots gave up to blocking sequential "
    "admission (live slots x chunk per admission prefill that paused "
    "the train); 0 by construction under fused staged admission — the "
    "fused-prefill before/after number",
)
PREFIX_CACHE_HIT_TOKENS = counter(
    "prefix_cache_hit_tokens",
    "prompt tokens whose KV was spliced from the shared-prefix radix "
    "cache instead of being re-prefilled (the device time the cache "
    "saves)",
)
PREFIX_CACHE_EVICTIONS = counter(
    "prefix_cache_evictions",
    "shared-prefix KV blocks evicted under the block budget (LRU "
    "unpinned leaves; blocks a live slot references are never freed)",
)
PREFIX_CACHE_BLOCKS_USED = gauge(
    "prefix_cache_blocks_used",
    "shared-prefix KV blocks currently resident in the radix tree "
    "(may transiently exceed the budget while every leaf is pinned)",
)
PREFIX_CACHE_HIT_RATE = gauge(
    "prefix_cache_hit_rate",
    "cumulative fraction of admitted prompt tokens served from the "
    "shared-prefix cache (hit tokens / prompt tokens since queue start)",
)
SERVING_TOKENS_PER_S = gauge(
    "serving_tokens_per_s",
    "recent serving throughput on the paged engine: emitted tokens per "
    "second over the last few seconds of reaps — the utilization "
    "numerator the capacity model divides by the chip's saturation "
    "ceiling (BENCH_NOTES: ~61.5k tok/s int8 at batch 128+)",
)
SERVING_QUEUE_DEPTH = gauge(
    "serving_queue_depth",
    "requests admitted but not yet in a device batch (the bound "
    "`max_queue` is enforced against), sampled at each scheduling "
    "round — queue growth at flat tokens/s is the saturation signal "
    "the capacity model and autoscaler watch",
)
SERVING_TP = gauge(
    "serving_tp",
    "tensor-parallel ways of the serving engine's mesh — the factor the "
    "paged KV planes shard their heads axis by (partition."
    "PAGED_PLANE_SPECS), joining per-chip gauges back to the mesh they "
    "were measured on",
)
SERVING_KV_BYTES_PER_CHIP = gauge(
    "serving_kv_bytes_per_chip",
    "HBM the paged slot KV working set occupies on EACH chip at the "
    "current cache width (total KV bytes / tp — the heads-axis sharding "
    "splits the planes evenly) — the per-node residency ceiling "
    "multi-chip paged serving raises to chip-count x HBM",
)

# Background bulk-scoring tenant (engine/scoring.py + engine/batcher.py):
# idle-lane harvest — preemptible score quanta co-scheduled behind
# interactive traffic, driving the chip toward its saturation ceiling.

SCORING_TOKENS_PER_S = gauge(
    "scoring_tokens_per_s",
    "recent background-scoring throughput: tokens scored per second over "
    "the last few seconds of quanta — the scoring tenant's half of the "
    "tenant-split utilization view (serving_tokens_per_s is the "
    "interactive half)",
)
SCORING_UTILIZATION = gauge(
    "scoring_utilization",
    "scoring_tokens_per_s as a fraction of the measured chip saturation "
    "ceiling (BENCH_NOTES: ~61.5k tok/s int8 at batch 128+) — how much "
    "of the idle headroom the background tenant is actually harvesting",
)
SCORING_QUANTA = counter(
    "scoring_quanta",
    "single-dispatch scoring quanta executed (one batch-bucket forward "
    "each — the preemption granularity interactive arrivals wait behind "
    "at most one of)",
)
SCORING_SCORED_TOKENS = counter(
    "scoring_scored_tokens",
    "corpus tokens the background tenant has scored (bulk grading / "
    "relevance / calibration texts; the cumulative companion of the "
    "scoring_tokens_per_s gauge)",
)
SCORING_JOBS_COMPLETED = counter(
    "scoring_jobs_completed",
    "bulk score jobs run to completion by the background tenant",
)
SCORING_JOBS_FAILED = counter(
    "scoring_jobs_failed",
    "bulk score jobs that failed (the job fails; the serving loop and "
    "other jobs keep going)",
)
SCORE_TRUNCATED_TEXTS = counter(
    "score_truncated_texts",
    "scored texts longer than the length-bucket limit whose PREFIX was "
    "scored (each carries a per-item truncated flag so relevance evals "
    "can't silently read a prefix score as a full-document score)",
)
SCORE_PREEMPT_WAIT_MS = counter(
    "score_preempt_wait_ms",
    "milliseconds interactive requests waited behind an in-flight "
    "scoring quantum before admission resumed (bounded by one quantum "
    "per arrival — the scoring tenant's preemption-latency account)",
)

# Per-program engine dispatch wall time (host-side: the time the serving
# loop spends issuing each compiled program; device compute overlaps it
# under pipelining). Names key the program-inventory entries — the
# serving queues map the engine's reported program name through
# ENGINE_PROGRAM_HISTOGRAMS below, and the same measurements become
# `engine.<program>` spans on the request trace.

ENGINE_PROG_PREFILL = histogram(
    "engine_prog_prefill",
    "paged-engine _prefill program dispatch wall time (one fresh-slot "
    "prompt pass)",
)
ENGINE_PROG_INSTALL = histogram(
    "engine_prog_install",
    "paged-engine _install program dispatch wall time (splicing a "
    "prefilled slot into the live state)",
)
ENGINE_PROG_STEP = histogram(
    "engine_prog_step",
    "paged-engine _step/_spec_step program dispatch wall time (one "
    "chunk of decode scan iterations)",
)
ENGINE_PROG_MEGASTEP = histogram(
    "engine_prog_megastep",
    "paged-engine _megastep program dispatch wall time (K chunks of "
    "decode fused into one device-resident dispatch)",
)
ENGINE_PROG_PARTIAL_PREFILL = histogram(
    "engine_prog_partial_prefill",
    "paged-engine _partial_prefill program dispatch wall time (a "
    "shared-prefix cache hit's suffix-only prompt pass)",
)
ENGINE_PROG_GROW = histogram(
    "engine_prog_grow",
    "paged-engine _grow program dispatch wall time (cache width "
    "transition)",
)
ENGINE_PROG_STAGE = histogram(
    "engine_prog_stage",
    "paged-engine _stage program dispatch wall time (fused admission: "
    "arming a slot's staged prompt; the prefill itself runs inside the "
    "megastep scan)",
)
ENGINE_PROG_SCORE = histogram(
    "engine_prog_score",
    "score program dispatch wall time (one background-scoring quantum: "
    "a full-sequence batch-bucket forward — the preemption granularity)",
)
ENGINE_PROG_GENERATE = histogram(
    "engine_prog_generate",
    "bucketed-engine generate dispatch wall time (one grouped device "
    "batch, prefill through last token)",
)

# Engine-reported program name -> declared histogram, used by the serving
# queues (engine/batcher.py). Living HERE keeps the mapping inside the
# declared namespace (see BREAKER_TRANSITION_COUNTERS).
ENGINE_PROGRAM_HISTOGRAMS: Dict[str, str] = {
    "prefill": ENGINE_PROG_PREFILL,
    "partial_prefill": ENGINE_PROG_PARTIAL_PREFILL,
    "install": ENGINE_PROG_INSTALL,
    "step": ENGINE_PROG_STEP,
    "megastep": ENGINE_PROG_MEGASTEP,
    "grow": ENGINE_PROG_GROW,
    "stage": ENGINE_PROG_STAGE,
    "score": ENGINE_PROG_SCORE,
    "generate": ENGINE_PROG_GENERATE,
}

# Storage layer (raft/storage.py + lms/persistence.py via lms/node.py).

WAL_TORN_TAIL_TRUNCATIONS = counter(
    "wal_torn_tail_truncations",
    "Raft WAL replays that dropped a torn final record (crash mid-append; "
    "the record was never acked durable)",
)
WAL_CORRUPT_RECORDS = counter(
    "wal_corrupt_records",
    "Raft WAL records that failed CRC/framing checks mid-file (bit rot / "
    "merged short write) — the node refuses to trust the log and recovers "
    "per [storage].recovery",
)
SNAPSHOT_INTEGRITY_FAILURES = counter(
    "snapshot_integrity_failures",
    "LMS state snapshots that failed their integrity header check at load",
)
STORAGE_RECOVERING = gauge(
    "storage_recovering",
    "1 while this node has discarded corrupt local storage and is "
    "rejoining via leader replication / InstallSnapshot; 0 once healed",
)
STALE_TMP_FILES_REMOVED = counter(
    "stale_tmp_files_removed",
    "orphaned atomic-write temp files (.raftwal.* / .lmssnap.* / .blob*) "
    "swept at boot, leaked by a crash between mkstemp and rename",
)

# Chaos admin plane (utils/faults.py CampaignRunner, serving/lms_server.py).

FAULT_CAMPAIGN_PHASES = counter(
    "fault_campaign_phases",
    "fault-campaign phases the admin plane applied (each phase installs "
    "one injector spec for its duration, then clears it)",
)

# Semester simulator (sim/): client-side series the harness exports in its
# BENCH record; the SLO checker reads them next to the cluster's /metrics.

SIM_OPS_OK = counter(
    "sim_ops_ok", "simulated student/instructor ops that succeeded"
)
SIM_OPS_FAILED = counter(
    "sim_ops_failed",
    "simulated ops that failed terminally (retries and budget exhausted)",
)
SIM_OPS_DROPPED = counter(
    "sim_ops_dropped",
    "simulated ops shed unexecuted because their worker fell further "
    "behind the trace than the lag bound (closed-loop overload, not a "
    "cluster failure)",
)
SIM_OP_LATENCY = histogram(
    "sim_op_latency", "client-observed latency of every simulated op"
)
SIM_ASK_LATENCY = histogram(
    "sim_ask_latency",
    "client-observed ask_llm latency (its p95 is the semester-sim answer "
    "SLO)",
)
SIM_DEGRADED_ANSWERS = counter(
    "sim_degraded_answers",
    "ask_llm calls answered by the degraded instructor-queue fallback, "
    "as seen by the simulated clients",
)
SIM_EVENTS_INJECTED = counter(
    "sim_events_injected",
    "operations-schedule events the semester sim executed (transfers, "
    "quarantines, membership changes, chaos campaigns)",
)
SIM_RYW_VIOLATIONS = counter(
    "sim_ryw_violations",
    "read-your-writes violations the in-run ledger auditor observed "
    "(a write acked before the read started was not visible)",
)
SIM_ACKED_WRITE_LOSSES = counter(
    "sim_acked_write_losses",
    "acked writes the end-of-run ledger audit could not find in the "
    "cluster (the zero-acked-write-loss SLO; must stay 0)",
)
SIM_SLO_VIOLATIONS = counter(
    "sim_slo_violations", "semester-sim SLO checks that failed"
)
SIM_BURN_ALERTS = counter(
    "sim_burn_alerts",
    "burn-rate alerts the continuous SLO engine raised during the run "
    "(fast- and slow-window; each is also recorded as a timeline event "
    "and classified against the injected-fault phases in the verdict)",
)
SIM_SESSION_TURNS = counter(
    "sim_session_turns",
    "streamed follow-up-chain turns the simulated students completed "
    "(each is one StreamLLMAnswer call carrying a session id)",
)
SIM_SESSION_TURNS_FAILED = counter(
    "sim_session_turns_failed",
    "streamed session turns that failed terminally; the rest of that "
    "chain is abandoned (later turns need the transcript)",
)
SIM_STREAM_RESUMES = counter(
    "sim_stream_resumes",
    "client-observed resume-at-offset failovers: streamed asks that "
    "lost their stream after the first delivered byte and continued "
    "from the delivered token offset on a retry",
)
SIM_STREAM_DIGEST_MISMATCH = counter(
    "sim_stream_digest_mismatch",
    "streamed answers whose assembled text failed the final chunk's "
    "digest check — a duplicated or dropped token somewhere in the "
    "stream; the verdict requires 0",
)
SIM_TURN_TTFT = histogram(
    "sim_turn_ttft",
    "client-observed time to first streamed token per session turn "
    "(its p95 is the per-turn conversational SLO)",
)

# Raft runner (utils/guards.py LoopWatchdog wired by lms/node.py).

RAFT_TICK_LAG = histogram(
    "raft_tick_lag",
    "how late each Raft tick ran versus its schedule (stalls here are "
    "the precursor of spurious elections)",
)
RAFT_TICK_STALLS = counter(
    "raft_tick_stalls",
    "Raft ticks later than 10 heartbeat intervals (each also logged)",
)
RAFT_STATE_DIGEST = gauge(
    "raft_state_digest",
    "low 32 bits of the replica's state-digest chain at its applied "
    "index (LMSState.digest folded per apply; replicas of one group at "
    "the same applied index must report the same value — divergence "
    "here is state-machine nondeterminism)",
)

# Serving event loop (utils/guards.py LoopWatchdog heartbeat wired by the
# gRPC server entry points): handler stalls become visible series instead
# of being inferred from latency tails.

SERVING_TICK_LAG = histogram(
    "serving_tick_lag",
    "how late the serving event loop's heartbeat ran versus its schedule "
    "(a stall here means a handler blocked the loop)",
)
SERVING_TICK_STALLS = counter(
    "serving_tick_stalls",
    "serving-loop heartbeats later than the stall threshold (each also "
    "logged)",
)

# Lock-order auditing (utils/locks.py OrderedLock, debug recording mode):
# the runtime counterpart of the lock-order lint rule.

LOCK_ORDER_VIOLATIONS = counter(
    "lock_order_violations",
    "lock acquisitions that re-entered a held non-reentrant lock or "
    "closed a cycle in the live acquisition-order graph (recorded by "
    "utils/locks.py OrderedLock when debug recording is on; each also "
    "lands in locks.violations() with the offending edge)",
)


if __name__ == "__main__":  # pragma: no cover - convenience
    print(render_markdown_table())
