"""Seeded fault injection for the real gRPC paths.

The chaos tests used to live exclusively on `raft.node.MemNetwork` — an
in-process transport whose drop/partition hooks never exercise the actual
sockets, codecs, or timeout plumbing. `FaultInjector` moves the same
fault surface onto the wire: a seeded RNG decides, per *target* (a Raft
peer, or the LMS→tutoring hop), whether a send is dropped, delayed,
errored after delivery (response lost), or duplicated.

Targets are plain strings — `"raft:3"` for Raft traffic to peer 3,
`"tutoring"` for the LMS→tutoring forward, `"*"` as a wildcard fallback —
so one injector instance can shape an entire node's egress. Every sampled
fault is applied on every target: Raft duplicates re-send through
`FaultyTransport`, and tutoring duplicates re-send the forward in
`lms.service.GetLLMAnswer` (it used to be a silent no-op there while
`injected_total` still counted it). Specs are
mutable at runtime: the LMS admin endpoint (`POST /admin/faults`) toggles
them over HTTP, which is how the chaos-over-real-gRPC soak drives a live
cluster.

Determinism: one `random.Random(seed)` per injector; with a fixed seed and
a fixed call sequence the same faults fire, so soak failures replay.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import threading
from typing import Dict, Optional

from ..raft.node import Transport


class FaultInjected(ConnectionError):
    """An injected transport failure (callers treat it like a network
    error: retry/degrade, never crash)."""


@dataclasses.dataclass
class FaultSpec:
    """Per-target fault probabilities (all default to 'no fault')."""

    drop: float = 0.0        # P(request lost before delivery)
    error: float = 0.0       # P(response lost after delivery)
    delay_s: float = 0.0     # fixed added latency
    delay_jitter_s: float = 0.0  # + uniform[0, jitter)
    duplicate: float = 0.0   # P(request delivered twice)

    def clamped(self) -> "FaultSpec":
        return FaultSpec(
            drop=min(1.0, max(0.0, self.drop)),
            error=min(1.0, max(0.0, self.error)),
            delay_s=max(0.0, self.delay_s),
            delay_jitter_s=max(0.0, self.delay_jitter_s),
            duplicate=min(1.0, max(0.0, self.duplicate)),
        )


@dataclasses.dataclass
class FaultPlan:
    """The sampled decisions for one send."""

    drop: bool = False
    error: bool = False
    delay_s: float = 0.0
    duplicate: bool = False

    @property
    def any(self) -> bool:
        return self.drop or self.error or self.duplicate or self.delay_s > 0


class FaultInjector:
    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)            # guarded-by: _lock
        self._specs: Dict[str, FaultSpec] = {}     # guarded-by: _lock
        self._lock = threading.Lock()
        self._injected = 0                         # guarded-by: _lock

    @property
    def active(self) -> bool:
        with self._lock:
            return bool(self._specs)

    def configure(self, target: str, **kwargs) -> FaultSpec:
        """Set (replace) the spec for `target`; unknown keys raise so admin
        typos surface as HTTP 400 rather than silent no-ops."""
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"unknown fault field(s) {sorted(bad)} "
                             f"(known: {sorted(known)})")
        spec = FaultSpec(**{k: float(v) for k, v in kwargs.items()}).clamped()
        with self._lock:
            self._specs[target] = spec
        return spec

    def clear(self, target: Optional[str] = None) -> None:
        with self._lock:
            if target is None:
                self._specs.clear()
            else:
                self._specs.pop(target, None)

    def spec_for(self, target: str) -> Optional[FaultSpec]:
        with self._lock:
            return self._specs.get(target) or self._specs.get("*")

    def plan(self, target: str) -> FaultPlan:
        """Sample this send's faults (single RNG; lock keeps the stream
        coherent under concurrent sends)."""
        spec = self.spec_for(target)
        if spec is None:
            return FaultPlan()
        with self._lock:
            plan = FaultPlan(
                drop=self._rng.random() < spec.drop,
                error=self._rng.random() < spec.error,
                delay_s=spec.delay_s
                + (self._rng.random() * spec.delay_jitter_s
                   if spec.delay_jitter_s else 0.0),
                duplicate=self._rng.random() < spec.duplicate,
            )
            if plan.drop or plan.error or plan.duplicate:
                self._injected += 1
            return plan

    async def apply_pre(self, target: str) -> FaultPlan:
        """Sample + apply the pre-delivery faults (delay, drop); returns
        the plan so the caller can apply post-delivery faults too."""
        plan = self.plan(target)
        if plan.delay_s > 0:
            await asyncio.sleep(plan.delay_s)
        if plan.drop:
            raise FaultInjected(f"injected drop -> {target}")
        return plan

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "injected_total": self._injected,
                "targets": {
                    t: dataclasses.asdict(s) for t, s in self._specs.items()
                },
            }


class FaultyTransport(Transport):
    """Wraps a real transport (normally `raft.grpc_transport.GrpcTransport`)
    with the injector. Target keys are `"<prefix>:<peer_id>"` so Raft
    traffic to individual peers can be shaped independently."""

    def __init__(self, inner: Transport, injector: FaultInjector,
                 prefix: str = "raft"):
        self.inner = inner
        self.injector = injector
        self.prefix = prefix

    @property
    def addresses(self):
        # RaftNode syncs membership addresses into `transport.addresses`;
        # forward to the wrapped transport's live map.
        return getattr(self.inner, "addresses", None)

    async def send(self, peer: int, message):
        plan = await self.injector.apply_pre(f"{self.prefix}:{peer}")
        resp = await self.inner.send(peer, message)
        if plan.duplicate:
            # The peer processes the message twice (Raft RPCs are
            # idempotent by design — this verifies it over real sockets).
            resp = await self.inner.send(peer, message)
        if plan.error:
            raise FaultInjected(f"injected response loss <- {self.prefix}:{peer}")
        return resp

    async def close(self) -> None:
        await self.inner.close()
