"""Seeded fault injection for the real gRPC paths.

The chaos tests used to live exclusively on `raft.node.MemNetwork` — an
in-process transport whose drop/partition hooks never exercise the actual
sockets, codecs, or timeout plumbing. `FaultInjector` moves the same
fault surface onto the wire: a seeded RNG decides, per *target* (a Raft
peer, or the LMS→tutoring hop), whether a send is dropped, delayed,
errored after delivery (response lost), or duplicated.

Targets are plain strings — `"raft:3"` for Raft traffic to peer 3,
`"tutoring"` for the LMS→tutoring forward, `"*"` as a wildcard fallback —
so one injector instance can shape an entire node's egress. Every sampled
fault is applied on every target: Raft duplicates re-send through
`FaultyTransport`, and tutoring duplicates re-send the forward in
`lms.service.GetLLMAnswer` (it used to be a silent no-op there while
`injected_total` still counted it). Specs are
mutable at runtime: the LMS admin endpoint (`POST /admin/faults`) toggles
them over HTTP, which is how the chaos-over-real-gRPC soak drives a live
cluster.

Determinism: one `random.Random(seed)` per injector; with a fixed seed and
a fixed call sequence the same faults fire, so soak failures replay.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import threading
from typing import Dict, List, Optional

from ..raft.node import Transport
from . import metrics_registry as metric

log = logging.getLogger(__name__)


class FaultInjected(ConnectionError):
    """An injected transport failure (callers treat it like a network
    error: retry/degrade, never crash)."""


@dataclasses.dataclass
class FaultSpec:
    """Per-target fault probabilities (all default to 'no fault')."""

    drop: float = 0.0        # P(request lost before delivery)
    error: float = 0.0       # P(response lost after delivery)
    delay_s: float = 0.0     # fixed added latency
    delay_jitter_s: float = 0.0  # + uniform[0, jitter)
    duplicate: float = 0.0   # P(request delivered twice)

    def clamped(self) -> "FaultSpec":
        return FaultSpec(
            drop=min(1.0, max(0.0, self.drop)),
            error=min(1.0, max(0.0, self.error)),
            delay_s=max(0.0, self.delay_s),
            delay_jitter_s=max(0.0, self.delay_jitter_s),
            duplicate=min(1.0, max(0.0, self.duplicate)),
        )


@dataclasses.dataclass
class FaultPlan:
    """The sampled decisions for one send."""

    drop: bool = False
    error: bool = False
    delay_s: float = 0.0
    duplicate: bool = False

    @property
    def any(self) -> bool:
        return self.drop or self.error or self.duplicate or self.delay_s > 0


class FaultInjector:
    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)            # guarded-by: _lock
        self._specs: Dict[str, FaultSpec] = {}     # guarded-by: _lock
        self._lock = threading.Lock()
        self._injected = 0                         # guarded-by: _lock

    @property
    def active(self) -> bool:
        with self._lock:
            return bool(self._specs)

    def configure(self, target: str, **kwargs) -> FaultSpec:
        """Set (replace) the spec for `target`; unknown keys raise so admin
        typos surface as HTTP 400 rather than silent no-ops."""
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"unknown fault field(s) {sorted(bad)} "
                             f"(known: {sorted(known)})")
        spec = FaultSpec(**{k: float(v) for k, v in kwargs.items()}).clamped()
        with self._lock:
            self._specs[target] = spec
        return spec

    def clear(self, target: Optional[str] = None) -> None:
        with self._lock:
            if target is None:
                self._specs.clear()
            else:
                self._specs.pop(target, None)

    def spec_for(self, target: str) -> Optional[FaultSpec]:
        """Most-specific spec for `target`, with hierarchical fallback
        walked one `:` segment at a time: `raft:2:4` (group 2's hop to
        peer 4) falls back to `raft:2` (all of group 2's traffic), then
        `raft` (every group), then the `*` wildcard — so per-group chaos
        (`raft:<gid>`) composes with per-peer and whole-tier targets the
        way `tutoring:<i>`/`tutoring` already do, and one spec can still
        blanket a node's entire egress."""
        with self._lock:
            key = target
            while True:
                spec = self._specs.get(key)
                if spec is not None or ":" not in key:
                    break
                key = key.rsplit(":", 1)[0]
            return spec or self._specs.get("*")

    def plan(self, target: str) -> FaultPlan:
        """Sample this send's faults (single RNG; lock keeps the stream
        coherent under concurrent sends)."""
        spec = self.spec_for(target)
        if spec is None:
            return FaultPlan()
        with self._lock:
            plan = FaultPlan(
                drop=self._rng.random() < spec.drop,
                error=self._rng.random() < spec.error,
                delay_s=spec.delay_s
                + (self._rng.random() * spec.delay_jitter_s
                   if spec.delay_jitter_s else 0.0),
                duplicate=self._rng.random() < spec.duplicate,
            )
            if plan.drop or plan.error or plan.duplicate:
                self._injected += 1
            return plan

    async def apply_pre(self, target: str) -> FaultPlan:
        """Sample + apply the pre-delivery faults (delay, drop); returns
        the plan so the caller can apply post-delivery faults too."""
        plan = self.plan(target)
        if plan.delay_s > 0:
            await asyncio.sleep(plan.delay_s)
        if plan.drop:
            raise FaultInjected(f"injected drop -> {target}")
        return plan

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "injected_total": self._injected,
                "targets": {
                    t: dataclasses.asdict(s) for t, s in self._specs.items()
                },
            }


@dataclasses.dataclass(frozen=True)
class CampaignPhase:
    """One timed step of a chaos campaign: install `spec` on `target` for
    `duration_s`, then clear it. `target` routes like the one-shot admin
    plane: `"disk"` goes to the disk injector, anything else (including
    the `"*"` wildcard) to the network injector."""

    target: str
    duration_s: float
    spec: Dict[str, float]

    @staticmethod
    def from_json(raw: dict) -> "CampaignPhase":
        if "target" not in raw:
            raise ValueError("campaign phase needs a 'target'")
        duration = float(raw.get("duration_s", 0.0))
        if duration <= 0.0:
            raise ValueError("campaign phase needs duration_s > 0")
        spec = {k: v for k, v in raw.items()
                if k not in ("target", "duration_s")}
        return CampaignPhase(target=str(raw["target"]), duration_s=duration,
                             spec=spec)


class CampaignRunner:
    """Timed fault campaigns over one node's injectors.

    A campaign is a named sequence of `CampaignPhase`s the admin plane
    schedules in one POST instead of an operator hand-driving configure/
    clear pairs: each phase installs its spec, holds it for its duration,
    then clears that target before the next phase. `GET /admin/faults`
    reports the live phase so the semester simulator (and operators) can
    assert exactly what is injected mid-run.

    Runs on the node's event loop (started from the admin handler); all
    state is loop-confined. Cancellation — explicit or via a replacing
    campaign — clears every target the campaign touched, so a cancelled
    campaign can never strand a fault spec.
    """

    def __init__(self, faults: FaultInjector, disk_faults=None, metrics=None):
        self.faults = faults
        self.disk_faults = disk_faults
        self.metrics = metrics
        self._task: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self._name: Optional[str] = None           # guarded-by: event-loop
        self._phases: List[CampaignPhase] = []     # guarded-by: event-loop
        self._phase_index: int = -1                # guarded-by: event-loop
        self._completed: int = 0                   # guarded-by: event-loop

    @property
    def active(self) -> bool:
        return self._task is not None and not self._task.done()

    def start(self, name: str, phases: List[dict]) -> dict:
        """Parse + validate every phase up front (a typo'd field must fail
        the POST, not abort the campaign mid-run), then schedule."""
        parsed = [CampaignPhase.from_json(p) for p in phases]
        if not parsed:
            raise ValueError("campaign needs at least one phase")
        for p in parsed:  # validate spec fields without touching live specs
            if p.target == "disk":
                if self.disk_faults is None:
                    raise ValueError("no disk injector on this node")
                from .diskfaults import DiskFaultSpec

                known = {f.name for f in dataclasses.fields(DiskFaultSpec)}
            else:
                known = {f.name for f in dataclasses.fields(FaultSpec)}
            bad = set(p.spec) - known
            if bad:
                raise ValueError(
                    f"unknown fault field(s) {sorted(bad)} for target "
                    f"{p.target!r} (known: {sorted(known)})"
                )
        prior = self._task
        self.cancel()
        self._name, self._phases, self._phase_index = name, parsed, -1
        self._task = asyncio.ensure_future(self._run(parsed, prior=prior))
        self._task.add_done_callback(self._on_done)
        return self.snapshot()

    def cancel(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None
        self._phase_index = -1

    async def stop(self) -> None:
        """`cancel`, then wait for the teardown to land: by return, every
        spec the campaign installed has been cleared. The admin plane's
        cancel paths use this so the POST *response* snapshot never shows
        the cancelled campaign's spec as still installed (cancel() alone
        only schedules the task's finally-clear)."""
        task = self._task
        self.cancel()
        if task is not None and not task.done():
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:  # already logged by its done callback
                pass

    def snapshot(self) -> dict:
        phase = None
        if self.active and 0 <= self._phase_index < len(self._phases):
            p = self._phases[self._phase_index]
            phase = {"target": p.target, "duration_s": p.duration_s,
                     **p.spec}
        return {
            "active": self.active,
            "name": self._name,
            "phase_index": self._phase_index if self.active else None,
            "phases_total": len(self._phases),
            "phases_completed_total": self._completed,
            "phase": phase,
        }

    # ------------------------------------------------------------ internals

    async def _run(self, phases: List[CampaignPhase],
                   prior: Optional[asyncio.Task] = None) -> None:
        if prior is not None and not prior.done():
            # Serialize the handoff: the replaced campaign's finally-clear
            # must land BEFORE this campaign installs a spec on the same
            # target, or the old teardown would wipe the new phase.
            try:
                await prior
            except asyncio.CancelledError:
                if not prior.cancelled():
                    raise  # our own cancellation, not the predecessor's
            except Exception:  # already logged by its done callback
                pass
        for i, phase in enumerate(phases):
            if self._task is not asyncio.current_task():
                return  # superseded while waiting on the predecessor
            self._phase_index = i
            try:
                if phase.target == "disk":
                    self.disk_faults.configure(**phase.spec)
                else:
                    self.faults.configure(phase.target, **phase.spec)
                if self.metrics is not None:
                    self.metrics.inc(metric.FAULT_CAMPAIGN_PHASES)
                self._completed += 1
                await asyncio.sleep(phase.duration_s)
            finally:
                # Clear even on cancellation: a campaign must never strand
                # its spec past its lifetime.
                if phase.target == "disk":
                    self.disk_faults.clear()
                else:
                    self.faults.clear(phase.target)

    def _on_done(self, task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.warning("fault campaign %r failed: %s", self._name, exc)


class FaultyTransport(Transport):
    """Wraps a real transport (normally `raft.grpc_transport.GrpcTransport`)
    with the injector. Target keys are `"<prefix>:<peer_id>"` so Raft
    traffic to individual peers can be shaped independently."""

    def __init__(self, inner: Transport, injector: FaultInjector,
                 prefix: str = "raft"):
        self.inner = inner
        self.injector = injector
        self.prefix = prefix

    @property
    def addresses(self):
        # RaftNode syncs membership addresses into `transport.addresses`;
        # forward to the wrapped transport's live map.
        return getattr(self.inner, "addresses", None)

    async def send(self, peer: int, message):
        plan = await self.injector.apply_pre(f"{self.prefix}:{peer}")
        resp = await self.inner.send(peer, message)
        if plan.duplicate:
            # The peer processes the message twice (Raft RPCs are
            # idempotent by design — this verifies it over real sockets).
            resp = await self.inner.send(peer, message)
        if plan.error:
            raise FaultInjected(f"injected response loss <- {self.prefix}:{peer}")
        return resp

    async def close(self) -> None:
        await self.inner.close()
