"""Resilience primitives for the student-query path.

Every hop of that path (client → LMS leader → tutoring node → batcher →
device) previously had its own ad-hoc timeout and an immediate-retry loop;
an overloaded or half-dead cluster therefore burned TPU time computing
answers nobody was still waiting for — the classic tail-latency failure
mode ("The Tail at Scale", Dean & Barroso 2013). This module centralizes
the three mechanisms that beat raw speed at scale:

- `Deadline`: one request-scoped time budget, created where the request
  enters the system and *decremented at each hop* (encoded as the gRPC
  timeout, so `context.time_remaining()` recovers it server-side, plus an
  explicit metadata header for non-gRPC hops). Work whose budget is gone
  is shed *before* the expensive step, not after.
- `jittered_backoff`: full-jitter exponential backoff for retry loops
  (synchronized immediate retries from thousands of clients are what turn
  a blip into an outage).
- `CircuitBreaker`: closed → open → half-open around a dependency; when
  the dependency is down, callers fail over to the degraded path in O(1)
  instead of stacking timeouts.

Everything takes an injectable `clock` so the state machines are testable
without sleeping.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .locks import make_lock

# Metadata key carrying the remaining budget in milliseconds. A *relative*
# budget (not an absolute timestamp) survives clock skew between hosts; each
# hop re-anchors it against its own monotonic clock on receipt.
DEADLINE_METADATA_KEY = "x-deadline-budget-ms"

# Metadata key carrying the client's idempotency id for ONE logical request,
# stable across its retries. The wire contract is frozen (QueryRequest has
# no request_id field), so the id rides gRPC metadata: the LMS uses it to
# key server-side mutations performed on the client's behalf — specifically
# the degraded instructor-queue fallback, where a fresh id per retried
# attempt used to queue duplicate instructor entries (ROADMAP item a).
REQUEST_ID_METADATA_KEY = "x-request-id"

# Trailing-metadata keys the tutoring node attaches to every answer: which
# fleet member served it (threaded into the `tutoring.forward` span and
# the routing pool's snapshots, so waterfalls and the ledger can attribute
# answers), and the node's live serving-queue depth (a passive load signal
# the router folds in between `/healthz` polls).
SERVED_BY_METADATA_KEY = "x-served-by"
QUEUE_DEPTH_METADATA_KEY = "x-queue-depth"


def _metadata_value(metadata: Any, key: str) -> Optional[str]:
    """First value for `key` in a gRPC metadata sequence (pairs or a
    mapping — the sync and aio stacks disagree on the shape); None when
    absent. The single normalization point for every header this module
    defines."""
    if metadata is None:
        return None
    items = metadata.items() if hasattr(metadata, "items") else metadata
    for k, v in items:
        if k == key:
            return str(v)
    return None


def request_id_from_grpc_context(context: Any) -> Optional[str]:
    """The client's logical-request id from metadata; None when absent."""
    try:
        metadata = context.invocation_metadata()
    except Exception:
        return None
    return _metadata_value(metadata, REQUEST_ID_METADATA_KEY) or None


class Overloaded(Exception):
    """Admission refused: a bounded queue is full (maps to
    RESOURCE_EXHAUSTED on the wire)."""


class DeadlineExpired(Exception):
    """The request's time budget ran out (maps to DEADLINE_EXCEEDED)."""


class BreakerOpen(Exception):
    """The circuit breaker is open; the dependency is presumed down."""


class Deadline:
    """An absolute point on a monotonic clock; the request's total budget.

    Created once at the edge (`Deadline.after(seconds)`); every later hop
    asks `remaining()` / `timeout(cap=...)` for its slice and refuses work
    when `expired`.
    """

    __slots__ = ("_deadline", "_clock")

    def __init__(self, deadline: float, *, clock: Callable[[], float] = time.monotonic):
        self._deadline = float(deadline)
        self._clock = clock

    @classmethod
    def after(cls, budget_s: float, *,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + max(0.0, float(budget_s)), clock=clock)

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._deadline - self._clock())

    @property
    def expired(self) -> bool:
        return self._clock() >= self._deadline

    def timeout(self, cap: Optional[float] = None) -> float:
        """The per-attempt gRPC timeout for the next hop: the remaining
        budget, optionally capped (a hop must not consume the whole budget
        when the caller wants headroom for a fallback)."""
        rem = self.remaining()
        return rem if cap is None else min(rem, float(cap))

    def raise_if_expired(self, what: str = "request") -> None:
        if self.expired:
            raise DeadlineExpired(f"{what}: deadline expired")

    # ------------------------------------------------------------- encoding

    def to_metadata(self) -> List[Tuple[str, str]]:
        return [(DEADLINE_METADATA_KEY, str(int(self.remaining() * 1000.0)))]

    @classmethod
    def from_metadata(
        cls, metadata: Any, *, clock: Callable[[], float] = time.monotonic
    ) -> Optional["Deadline"]:
        """Decode the budget header from a gRPC metadata sequence (pairs or
        a mapping); None when absent or malformed."""
        value = _metadata_value(metadata, DEADLINE_METADATA_KEY)
        if value is None:
            return None
        try:
            return cls.after(int(value) / 1000.0, clock=clock)
        except (TypeError, ValueError):
            return None

    @classmethod
    def from_grpc_context(
        cls, context: Any, *, clock: Callable[[], float] = time.monotonic
    ) -> Optional["Deadline"]:
        """Recover the caller's budget server-side: the tighter of the
        native gRPC deadline (`context.time_remaining()`, propagated from
        the client's `timeout=`) and the explicit metadata header. None
        when the caller set neither (an unbounded request)."""
        budgets = []
        try:
            rem = context.time_remaining()
        except Exception:
            rem = None
        # grpc returns None (sync) or a huge float (aio uses None too) for
        # no-deadline calls; guard the nonsensical as well.
        if rem is not None and rem == rem and rem < 1e9:
            budgets.append(max(0.0, rem))
        try:
            md = context.invocation_metadata()
        except Exception:
            md = None
        from_md = cls.from_metadata(md, clock=clock)
        if from_md is not None:
            budgets.append(from_md.remaining())
        if not budgets:
            return None
        return cls.after(min(budgets), clock=clock)


def jittered_backoff(
    attempt: int,
    *,
    base_s: float = 0.05,
    factor: float = 2.0,
    cap_s: float = 2.0,
    rng: Optional[random.Random] = None,
) -> float:
    """Full-jitter exponential backoff: uniform in [0, min(cap, base·f^n)].

    Full jitter (vs. equal jitter) maximally decorrelates a retry herd —
    the property that matters when every student client re-resolves the
    same dead leader at once.
    """
    ceiling = min(float(cap_s), float(base_s) * float(factor) ** max(0, attempt))
    r = rng.random() if rng is not None else random.random()
    return r * ceiling


class CircuitBreaker:
    """Closed / open / half-open breaker around one dependency.

    - CLOSED: calls flow; `failure_threshold` *consecutive* failures open
      the circuit.
    - OPEN: `allow()` is False until `recovery_s` has elapsed, then the
      breaker moves to HALF_OPEN.
    - HALF_OPEN: up to `half_open_max` probe calls are allowed; one success
      closes the circuit, one failure re-opens it (and restarts the
      recovery clock).

    Thread-safe; the asyncio servers share one instance per dependency.
    `on_state_change(old, new)` lets callers mirror the state into metrics.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _STATE_CODES = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        recovery_s: float = 10.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_state_change: Optional[Callable[[str, str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_s = recovery_s
        self.half_open_max = max(1, half_open_max)
        self._clock = clock
        self._on_state_change = on_state_change
        # Named for the live acquisition-order graph (utils/locks.py);
        # the name matches the static analysis's short lock key.
        self._lock = make_lock("CircuitBreaker._lock")
        self._state = self.CLOSED        # guarded-by: _lock
        self._consecutive_failures = 0   # guarded-by: _lock
        self._opened_at = 0.0            # guarded-by: _lock
        self._half_open_inflight = 0     # guarded-by: _lock
        self._half_open_since = 0.0      # guarded-by: _lock
        # guarded-by: _lock
        self._stats = {"opened": 0, "rejected": 0, "failures": 0, "successes": 0}

    # ------------------------------------------------------------- internals

    def _transition(self, new_state: str) -> None:  # guarded-by: _lock
        old, self._state = self._state, new_state
        if new_state is self.OPEN:
            self._opened_at = self._clock()
            self._stats["opened"] += 1
        if new_state is self.HALF_OPEN:
            self._half_open_inflight = 0
            self._half_open_since = self._clock()
        if old != new_state and self._on_state_change is not None:
            cb = self._on_state_change
            # Outside the lock path would be nicer, but callbacks here are
            # metric writes (non-blocking, never re-entrant into allow()).
            cb(old, new_state)

    # ------------------------------------------------------------------ api

    def set_state_change_callback(
        self, cb: Optional[Callable[[str, str], None]]
    ) -> None:
        """(Re)wire the transition observer — lets the owner of the
        dependency (who knows how to log/export it) attach after the
        breaker was constructed elsewhere."""
        with self._lock:
            self._on_state_change = cb

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:  # guarded-by: _lock
        if (
            self._state is self.OPEN
            and self._clock() - self._opened_at >= self.recovery_s
        ):
            self._transition(self.HALF_OPEN)
        elif (
            self._state is self.HALF_OPEN
            and self._half_open_inflight >= self.half_open_max
            and self._clock() - self._half_open_since >= self.recovery_s
        ):
            # A probe slot leaked (its caller died between allow() and
            # record_*): re-arm after another recovery window instead of
            # wedging half-open with no capacity forever.
            self._half_open_since = self._clock()
            self._half_open_inflight = 0

    def allow(self) -> bool:
        """True when a call may proceed (counts a half-open probe slot)."""
        with self._lock:
            self._maybe_half_open()
            if self._state is self.CLOSED:
                return True
            if self._state is self.HALF_OPEN:
                if self._half_open_inflight < self.half_open_max:
                    self._half_open_inflight += 1
                    return True
            self._stats["rejected"] += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self._stats["successes"] += 1
            self._consecutive_failures = 0
            if self._state is not self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._stats["failures"] += 1
            self._consecutive_failures += 1
            if self._state is self.HALF_OPEN:
                self._transition(self.OPEN)
            elif (
                self._state is self.CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._transition(self.OPEN)

    def state_code(self) -> float:
        """Numeric encoding for a metrics gauge (0/1/2)."""
        return self._STATE_CODES[self.state]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            self._maybe_half_open()
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                **self._stats,
            }
