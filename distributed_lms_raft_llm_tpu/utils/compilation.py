"""Persistent XLA compilation cache setup.

Cold-start compiles for the serving programs are tens of seconds (the
round-1 bench paid 21.4 s per process). JAX can persist compiled
executables keyed by HLO fingerprint; enabling it once per process makes
every warm restart skip straight to execution. The reference has no
analogue (PyTorch eager), so this is pure TPU-platform work.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

_enabled = False


def enable_compilation_cache(path: Optional[str] = None) -> str:
    """Idempotently point JAX at an on-disk compilation cache.

    Resolution order: explicit `path` arg, `JAX_COMPILATION_CACHE_DIR`,
    then `~/.cache/dlrl_tpu/xla_cache`.
    """
    global _enabled
    import jax

    path = (
        path
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        # Keyed by backend platform: CPU and TPU processes sharing one dir
        # poisons CPU starts with AOT entries compiled for other targets /
        # other machines' vector features (observed: minutes of
        # cpu_aot_loader feature-mismatch churn before the server came up).
        or os.path.expanduser(
            f"~/.cache/dlrl_tpu/xla_cache_{jax.default_backend()}"
        )
    )
    if _enabled:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache every program that took non-trivial compile time; the decode
    # program is the one that matters and always clears this bar.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    log.info("XLA compilation cache at %s", path)
    return path
