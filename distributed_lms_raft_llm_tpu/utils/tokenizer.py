"""Tokenizers for the serving path — pure Python, zero network, no torch.

The reference delegates tokenization to HF `GPT2Tokenizer` /
`BertTokenizer` pulled from the hub (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10, lms_server.py:11). This image
has no network egress, so we implement the two algorithms directly and load
their vocab files from disk when available:

- `BPETokenizer`   — GPT-2's byte-level BPE, from `vocab.json` + `merges.txt`.
- `WordPieceTokenizer` — BERT's WordPiece, from `vocab.txt`.
- `ByteTokenizer`  — a self-contained byte-level fallback (ids 0..255 plus
  specials) used when no vocab files are configured; keeps the whole serving
  stack runnable end-to-end with randomly initialized models.

All expose: `encode(text) -> List[int]`, `decode(ids) -> str`,
`vocab_size`, `eos_id`, `pad_id`.
"""

from __future__ import annotations

import json
import unicodedata
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

import regex  # supports \p{L}/\p{N} — required for GPT-2's exact pattern


@lru_cache()
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte <-> printable-unicode mapping."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


# GPT-2's exact pre-tokenization pattern (contractions, unicode words,
# numbers, punctuation runs, trailing/other whitespace). \p classes matter:
# é is a letter, not punctuation — ASCII-only approximations break parity
# with HF on any non-English text.
_GPT2_PAT = regex.compile(
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


class BPETokenizer:
    """GPT-2 byte-level BPE from vocab.json + merges.txt."""

    def __init__(self, vocab: Dict[str, int], merges: Sequence[Tuple[str, str]]):
        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self._cache: Dict[str, List[str]] = {}
        self.eos_id = self.encoder.get("<|endoftext|>", len(self.encoder) - 1)
        self.pad_id = self.eos_id

    @classmethod
    def from_files(cls, vocab_path: str, merges_path: str) -> "BPETokenizer":
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(merges_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                if len(parts) == 2:
                    merges.append((parts[0], parts[1]))
        return cls(vocab, merges)

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word: List[str] = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if best not in self.bpe_ranks:
                break
            first, second = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == first and word[i + 1] == second:
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        self._cache[token] = word
        return word

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in _GPT2_PAT.findall(text):
            tok_bytes = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            for piece in self._bpe(tok_bytes):
                ids.append(self.encoder[piece])
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        data = bytearray(self.byte_decoder.get(ch, ord("?")) for ch in text)
        return data.decode("utf-8", errors="replace")


class WordPieceTokenizer:
    """BERT WordPiece from vocab.txt, with BERT basic (lowercase) pre-split."""

    def __init__(self, vocab: Dict[str, int], lowercase: bool = True):
        self.vocab = dict(vocab)
        self.ids_to_tokens = {v: k for k, v in self.vocab.items()}
        self.lowercase = lowercase
        self.unk_id = self.vocab.get("[UNK]", 0)
        self.cls_id = self.vocab.get("[CLS]", 0)
        self.sep_id = self.vocab.get("[SEP]", 0)
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.eos_id = self.sep_id

    @classmethod
    def from_file(cls, vocab_path: str, lowercase: bool = True) -> "WordPieceTokenizer":
        vocab = {}
        with open(vocab_path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return cls(vocab, lowercase)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @staticmethod
    def _is_punct(ch: str) -> bool:
        # BERT's definition: ASCII symbol ranges (treated as punctuation even
        # where unicode says otherwise, e.g. $ ^ `) or any unicode P category.
        cp = ord(ch)
        if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
            return True
        return unicodedata.category(ch).startswith("P")

    @staticmethod
    def _is_cjk(ch: str) -> bool:
        cp = ord(ch)
        return (
            0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
        )

    def _split(self, text: str) -> List[str]:
        """BERT basic tokenization: clean, CJK-space, lowercase+strip accents,
        whitespace-split, then isolate punctuation (matches HF BertTokenizer's
        BasicTokenizer so WordPiece sees identical words)."""
        cleaned = []
        for ch in text:
            cp = ord(ch)
            cat = unicodedata.category(ch)
            if cp == 0 or cp == 0xFFFD or (cat.startswith("C") and ch not in "\t\n\r"):
                continue
            if ch in "\t\n\r" or cat == "Zs":
                cleaned.append(" ")
            elif self._is_cjk(ch):
                cleaned.append(f" {ch} ")
            else:
                cleaned.append(ch)
        text = "".join(cleaned)
        if self.lowercase:
            text = text.lower()
            text = "".join(
                ch for ch in unicodedata.normalize("NFD", text)
                if unicodedata.category(ch) != "Mn"
            )
        out: List[str] = []
        for chunk in text.split():
            cur = ""
            for ch in chunk:
                if self._is_punct(ch):
                    if cur:
                        out.append(cur)
                        cur = ""
                    out.append(ch)
                else:
                    cur += ch
            if cur:
                out.append(cur)
        return out

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > 100:
            return [self.unk_id]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    piece_id = self.vocab[piece]
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            ids.append(piece_id)
            start = end
        return ids

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        ids: List[int] = []
        for word in self._split(text):
            ids.extend(self._wordpiece(word))
        if add_special_tokens:
            ids = [self.cls_id] + ids + [self.sep_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        toks = [self.ids_to_tokens.get(int(i), "[UNK]") for i in ids]
        out = []
        for t in toks:
            if t in ("[CLS]", "[SEP]", "[PAD]"):
                continue
            if t.startswith("##") and out:
                out[-1] += t[2:]
            else:
                out.append(t)
        return " ".join(out)


class ByteTokenizer:
    """Fallback: UTF-8 bytes as ids 0..255; specials above.

    Keeps every text path (serving, gate, tests, demos) runnable without any
    vocab files. id 256 = BOS/EOS/pad.
    """

    def __init__(self, vocab_size: int = 257):
        assert vocab_size >= 257
        self._vocab_size = vocab_size
        self.eos_id = 256
        self.pad_id = 256
        self.cls_id = 256
        self.sep_id = 256

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_special_tokens:
            ids = [self.cls_id] + ids + [self.sep_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in (int(x) for x in ids) if i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Adapter over a HF `tokenizer.json` via the `tokenizers` library.

    This is the format Llama-3-style checkpoints ship (tiktoken-flavored
    byte-level BPE with a custom pre-tokenizer); wrapping the rust
    tokenizer gives exact parity for any architecture whose vocab isn't
    plain GPT-2 vocab.json+merges.txt. Offline: reads only the local file.
    """

    def __init__(self, path: str):
        import tokenizers

        self._tok = tokenizers.Tokenizer.from_file(path)
        self._vocab = self._tok.get_vocab()
        specials = [
            t for t in ("<|end_of_text|>", "<|endoftext|>", "</s>", "<|eot_id|>")
            if t in self._vocab
        ]
        self.eos_id = self._vocab[specials[0]] if specials else (
            self._tok.get_vocab_size() - 1
        )
        self.pad_id = self.eos_id

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=True)


def load_gpt2_tokenizer(
    vocab_path: Optional[str] = None,
    merges_path: Optional[str] = None,
    tokenizer_json: Optional[str] = None,
):
    """Serving tokenizer resolution: HF tokenizer.json (any architecture,
    e.g. Llama) > GPT-2 vocab.json+merges.txt BPE > byte fallback."""
    if tokenizer_json:
        return HFTokenizer(tokenizer_json)
    if vocab_path and merges_path:
        return BPETokenizer.from_files(vocab_path, merges_path)
    return ByteTokenizer()


def load_bert_tokenizer(vocab_path: Optional[str] = None):
    if vocab_path:
        return WordPieceTokenizer.from_file(vocab_path)
    return ByteTokenizer()
