"""Flight-recorder request tracing: span timelines from click to chip.

The repo exports 40+ aggregate metric series, but an aggregate cannot say
where ONE request's 1.69 s went — every per-stage question ("was it the
Raft commit? the gate? queue wait? an engine program?") previously meant
guesswork across four processes' logs. This module is a dependency-free
Dapper-style tracer (Sigelman et al., 2010) sized for this codebase:

- **Span trees.** `tracer.trace(name)` opens a request-scoped root;
  `tracer.span(name)` nests under the contextvar-tracked current span.
  Durations come from the monotonic clock; absolute positions from the
  wall clock, so fragments recorded by different processes line up on one
  waterfall without sharing a monotonic epoch.
- **Cross-process propagation.** `trace_metadata()` appends an
  `x-trace-context` header (`<trace_id>/<span_id>`) to outgoing gRPC
  metadata, riding the same plumbing as `x-request-id` and
  `x-deadline-budget-ms`; `continue_from_grpc_context()` reconstitutes
  the caller's position as a remote-parented fragment. The client's
  logical request id doubles as the trace id, so `GET
  /admin/trace/<request-id>` answers for exactly the id already in logs.
- **Flight recorder.** The store is a bounded ring (`[tracing]
  ring_size`), but anomalies are never sampled away: every trace flagged
  degraded / error / deadline-exhausted is pinned, and so are the
  slowest-N per route ("the Mystery Machine" exemplar idea, OSDI '14) —
  a perf regression arrives with its own span timeline attached.

One process-global tracer (`get_tracer()`) serves every component, so the
in-process semester-sim cluster assembles complete client→engine trees;
real multi-process deployments each retain their fragment and
`scripts/trace_report.py` merges fragments fetched from several
`/admin/trace` endpoints. Raft-internal RPCs (heartbeats, appends) are
deliberately untraced: at tick rate they would churn the ring and say
nothing a request-scoped `raft.commit` span doesn't.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import copy
import functools
import inspect
import random
import threading
import time
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

from .resilience import REQUEST_ID_METADATA_KEY, _metadata_value

# Metadata key carrying `<trace_id>/<span_id>` of the caller's position.
TRACE_METADATA_KEY = "x-trace-context"

# Flight-recorder flags: traces carrying any of these are pinned past
# ring eviction (the anomalies a sampled store would lose first).
FLAG_DEGRADED = "degraded"
FLAG_ERROR = "error"
FLAG_DEADLINE = "deadline_exhausted"


def _new_id() -> str:
    """64-bit hex id. Uniqueness-for-correlation, not cryptographic."""
    return f"{random.getrandbits(64):016x}"


class Span:
    """One timed operation. Mutated only by its owning thread/task until
    `end()`; afterwards read-only (the store renders it under its lock)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_unix",
        "_t0", "duration_s", "attrs", "status", "children", "root",
        "flags", "_tracer", "_token",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent: Optional["Span"],
        parent_id: Optional[str],
        attrs: Optional[Dict[str, Any]],
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent.span_id if parent is not None else parent_id
        self.start_unix = tracer._wall()
        self._t0 = tracer._clock()
        self.duration_s: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.status = "ok"
        self.children: List["Span"] = []
        # The fragment root (self, for roots): flags and completion are
        # tracked there; `flag()` on any descendant marks the fragment.
        self.root: "Span" = parent.root if parent is not None else self
        self.flags: set = set()
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None
        if parent is not None:
            parent.children.append(self)

    # ------------------------------------------------------------- mutation

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def set_status(self, status: str) -> "Span":
        self.status = status
        return self

    def flag(self, name: str) -> "Span":
        """Mark this span's whole fragment anomalous: the flight recorder
        pins the trace so it survives ring eviction."""
        self.root.flags.add(name)
        return self

    def child(self, name: str, **attrs: Any) -> "Span":
        """Manually-managed child (for code that cannot use the context
        manager — e.g. the batcher tracking queue wait across tasks).
        Starts now; the caller must `end()` it."""
        return Span(self._tracer, name, self.trace_id, self, None, attrs)

    def child_timed(
        self, name: str, start_unix: float, duration_s: float,
        **attrs: Any,
    ) -> "Span":
        """After-the-fact child for an interval measured elsewhere (engine
        program dispatches record (name, start, duration) tuples on the
        engine thread and are attached here at reap time)."""
        sp = Span(self._tracer, name, self.trace_id, self, None, attrs)
        sp.start_unix = start_unix
        sp.duration_s = max(0.0, float(duration_s))
        return sp

    def end(self, duration_s: Optional[float] = None) -> None:
        """Close the span. `duration_s` overrides the measured wall time
        when the true interval was measured elsewhere (queue wait measured
        by the engine, reported at reap)."""
        if self.duration_s is not None:
            return  # idempotent: a double end keeps the first measurement
        self.duration_s = (
            max(0.0, float(duration_s)) if duration_s is not None
            else self._tracer._clock() - self._t0
        )
        if self is self.root:
            self._tracer._record_fragment(self)

    # ------------------------------------------------------------ rendering

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name,
            "span_id": self.span_id,
            "start_s": self.start_unix,
            "duration_s": round(
                self.duration_s if self.duration_s is not None
                else self._tracer._clock() - self._t0, 6,
            ),
        }
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.status != "ok":
            out["status"] = self.status
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


def _dict_span_count(span: Dict[str, Any]) -> int:
    return 1 + sum(_dict_span_count(c) for c in span.get("children", ()))


def _trim_to_budget(span: Dict[str, Any], budget: int) -> int:
    """Truncate a span-dict subtree in place to at most `budget` spans,
    preorder keep-first (`budget` >= 1: the span itself always survives).
    Returns the number of spans kept."""
    kept = 1
    keep: List[Dict[str, Any]] = []
    for child in span.get("children", ()):
        if kept >= budget:
            break
        kept += _trim_to_budget(child, budget - kept)
        keep.append(child)
    if "children" in span:
        if keep:
            span["children"] = keep
        else:
            del span["children"]
    return kept


class _NullSpan:
    """No-op span: what `span()` yields outside any trace (and everything
    when tracing is disabled), so instrumentation never branches."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    duration_s = 0.0
    attrs: Dict[str, Any] = {}

    def set_attr(self, key: str, value: Any) -> "_NullSpan":
        return self

    def set_status(self, status: str) -> "_NullSpan":
        return self

    def flag(self, name: str) -> "_NullSpan":
        return self

    def child(self, name: str, **attrs: Any) -> "_NullSpan":
        return self

    def child_timed(self, name: str, start_unix: float, duration_s: float,
                    **attrs: Any) -> "_NullSpan":
        return self

    def end(self, duration_s: Optional[float] = None) -> None:
        pass


NULL_SPAN = _NullSpan()


class _TraceRecord:
    """Everything retained for one trace id."""

    __slots__ = ("trace_id", "route", "start_unix", "duration_s", "flags",
                 "fragments", "span_total", "pins", "wall_last")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.route = ""
        self.start_unix = float("inf")
        self.duration_s = 0.0
        self.flags: set = set()
        # Pure-dict snapshots (`Span.to_dict` at record time): immutable
        # w.r.t. late Span-tree mutation, rendered without re-walking.
        self.fragments: List[Dict[str, Any]] = []
        self.span_total = 0
        self.pins: set = set()
        self.wall_last = 0.0

    def summary(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "route": self.route,
            "duration_s": round(self.duration_s, 6),
            "flags": sorted(self.flags),
            "spans": self.span_total,
            "pinned": sorted(self.pins),
        }


class Tracer:
    """Span factory + the bounded flight-recorder store."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        ring_size: int = 256,
        exemplars_per_route: int = 4,
        flagged_max: int = 64,
        max_spans_per_trace: int = 512,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self.enabled = enabled
        self.ring_size = max(1, int(ring_size))
        self.exemplars_per_route = max(0, int(exemplars_per_route))
        self.flagged_max = max(0, int(flagged_max))
        self.max_spans_per_trace = max(1, int(max_spans_per_trace))
        self._clock = clock
        self._wall = wall
        self._current: contextvars.ContextVar[Optional[Span]] = (
            contextvars.ContextVar("dlrl_current_span", default=None)
        )
        self._lock = threading.Lock()
        self._records: Dict[str, _TraceRecord] = {}     # guarded-by: _lock
        # Unpinned retention order (ring membership only; pinned records
        # live solely in _records until unpinned back into the ring).
        self._ring: "collections.OrderedDict[str, None]" = (  # guarded-by: _lock
            collections.OrderedDict()
        )
        # Flagged pin order, oldest first (bounded by flagged_max).
        self._flagged: "collections.OrderedDict[str, None]" = (  # guarded-by: _lock
            collections.OrderedDict()
        )
        # route -> min-heap-ish list of (duration_s, trace_id).
        self._slowest: Dict[str, List[Tuple[float, str]]] = {}  # guarded-by: _lock

    # ------------------------------------------------------------- spanning

    def current(self) -> Optional[Span]:
        return self._current.get()

    @contextlib.contextmanager
    def trace(
        self, name: str, trace_id: Optional[str] = None, **attrs: Any
    ) -> Iterator[Any]:
        """Open a new root span (a fresh trace)."""
        if not self.enabled:
            yield NULL_SPAN
            return
        span = Span(self, name, trace_id or _new_id(), None, None, attrs)
        yield from self._run_span(span)

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Any]:
        """Child of the current span; a no-op outside any trace, so
        instrumentation sites never need to know whether the request
        entered through a traced edge."""
        parent = self._current.get()
        if parent is None or not self.enabled:
            yield NULL_SPAN
            return
        span = Span(self, name, parent.trace_id, parent, None, attrs)
        yield from self._run_span(span)

    @contextlib.contextmanager
    def continue_trace(
        self, name: str, trace_id: str, parent_span_id: Optional[str],
        **attrs: Any,
    ) -> Iterator[Any]:
        """A remote-parented fragment root: this process's piece of a
        trace whose parent span lives in the calling process."""
        if not self.enabled:
            yield NULL_SPAN
            return
        span = Span(self, name, trace_id, None, parent_span_id, attrs)
        yield from self._run_span(span)

    def _run_span(self, span: Span) -> Iterator[Span]:
        token = self._current.set(span)
        try:
            yield span
        except BaseException:
            span.set_status("error")
            span.flag(FLAG_ERROR)
            raise
        finally:
            try:
                self._current.reset(token)
            except ValueError:
                # Spans opened inside an async generator can be entered
                # from one task and unwound from another (a hedged first
                # read advances the generator in the race task; the
                # caller's task closes it).  The entering task's context
                # copy dies with that task, so there is nothing to
                # restore here — and the original exception must keep
                # propagating untouched.
                pass
            span.end()

    def continue_from_grpc_context(
        self, context: Any, name: str, **attrs: Any
    ):
        """Fragment root for a server-side handler: parented on the
        caller's `x-trace-context` when present, otherwise a fresh trace
        whose id is the caller's `x-request-id` (so untraced-but-ided
        clients still get `/admin/trace/<request-id>`), otherwise random.
        """
        if not self.enabled:
            return self.trace(name)  # the disabled no-op path
        try:
            md = context.invocation_metadata()
        except Exception:
            md = None
        parsed = parse_trace_context(_metadata_value(md, TRACE_METADATA_KEY))
        if parsed is not None:
            return self.continue_trace(name, parsed[0], parsed[1], **attrs)
        rid = _metadata_value(md, REQUEST_ID_METADATA_KEY)
        return self.trace(name, trace_id=rid or None, **attrs)

    # ---------------------------------------------------------- propagation

    def context_header(self) -> Optional[Tuple[str, str]]:
        span = self._current.get()
        if span is None or not self.enabled:
            return None
        return (TRACE_METADATA_KEY, f"{span.trace_id}/{span.span_id}")

    # ---------------------------------------------------------------- store

    def _record_fragment(self, root: Span) -> None:
        # Snapshot BEFORE storing: a late child attached to the live Span
        # tree after the fragment ended (a batcher finishing a device
        # batch whose handler was cancelled mid-flight) must not mutate
        # the recorded tree under the admin plane's renderer, nor dodge
        # the per-trace span accounting.
        snap = root.to_dict()
        n = _dict_span_count(snap)
        with self._lock:
            rec = self._records.get(root.trace_id)
            if rec is None:
                rec = _TraceRecord(root.trace_id)
                self._records[root.trace_id] = rec
                self._ring[root.trace_id] = None
            budget = self.max_spans_per_trace - rec.span_total
            if n > budget:
                # Keep-first-N, not drop-all: the runaway request is
                # exactly the trace the flight recorder exists to keep.
                rec.flags.add("truncated")
                if budget > 0:
                    rec.span_total += _trim_to_budget(snap, budget)
                    rec.fragments.append(snap)
            else:
                rec.fragments.append(snap)
                rec.span_total += n
            rec.flags |= root.flags
            rec.wall_last = self._wall()
            # The outermost fragment (earliest start) names the route and
            # the headline duration.
            if root.start_unix < rec.start_unix or not rec.route:
                old_route = rec.route
                rec.start_unix = root.start_unix
                rec.route = root.name
                rec.duration_s = root.duration_s or 0.0
                if old_route and old_route != rec.route:
                    # Renamed (the outermost client fragment landed after
                    # a handler fragment): leave exactly ONE route heap —
                    # a stale entry in the old heap would both block that
                    # route's future exemplars and let displacement there
                    # strip the pin this route still relies on.
                    self._drop_slowest_entry(old_route, rec.trace_id)
            if root.trace_id in self._ring:
                self._ring.move_to_end(root.trace_id)
            self._pin_if_anomalous(rec)
            self._pin_if_slow(rec)
            self._evict()

    def _pin(self, rec: _TraceRecord, pin: str) -> None:  # guarded-by: _lock
        rec.pins.add(pin)
        self._ring.pop(rec.trace_id, None)

    def _unpin(self, trace_id: str, pin: str) -> None:  # guarded-by: _lock
        rec = self._records.get(trace_id)
        if rec is None:
            return
        rec.pins.discard(pin)
        if not rec.pins and trace_id not in self._ring:
            # Re-enter the ring at the OLD end and enforce the bound: an
            # ex-pin (displaced exemplar, aged-out flagged FIFO entry) is
            # ordinary retention again and must not outrank genuinely
            # newer traces — appending it as newest let a displaced
            # exemplar linger past ring_size fresher records (the
            # "retained but neither pinned nor recent" hole
            # tests/test_tracing.py::test_ring_evicts_oldest_unpinned
            # catches under load-jittered durations).
            self._ring[trace_id] = None
            self._ring.move_to_end(trace_id, last=False)
            self._evict()

    def _pin_if_anomalous(self, rec: _TraceRecord) -> None:  # guarded-by: _lock
        if not (rec.flags - {"truncated"}) or self.flagged_max == 0:
            return
        if rec.trace_id not in self._flagged:
            self._flagged[rec.trace_id] = None
        self._pin(rec, "flagged")
        while len(self._flagged) > self.flagged_max:
            old, _ = self._flagged.popitem(last=False)
            self._unpin(old, "flagged")

    def _drop_slowest_entry(self, route: str, trace_id: str) -> None:  # guarded-by: _lock
        heap = self._slowest.get(route)
        if not heap:
            return
        kept = [(d, t) for d, t in heap if t != trace_id]
        if len(kept) != len(heap):
            self._slowest[route] = kept
            self._unpin(trace_id, "slowest")

    def _pin_if_slow(self, rec: _TraceRecord) -> None:  # guarded-by: _lock
        if self.exemplars_per_route == 0 or not rec.route:
            return
        heap = self._slowest.setdefault(rec.route, [])
        for i, (dur, tid) in enumerate(heap):
            if tid == rec.trace_id:
                # A later fragment extended this trace: refresh in place.
                heap[i] = (max(dur, rec.duration_s), tid)
                heap.sort()
                return
        if len(heap) < self.exemplars_per_route:
            heap.append((rec.duration_s, rec.trace_id))
            heap.sort()
            self._pin(rec, "slowest")
        elif heap and rec.duration_s > heap[0][0]:
            _, displaced = heap[0]
            heap[0] = (rec.duration_s, rec.trace_id)
            heap.sort()
            self._unpin(displaced, "slowest")
            self._pin(rec, "slowest")

    def _evict(self) -> None:  # guarded-by: _lock
        # `ring_size` bounds the UNPINNED ring only — pins (flagged +
        # slowest-per-route, themselves bounded) ride on top, so a burst
        # of anomalies can never starve the recent-trace window.
        while len(self._ring) > self.ring_size:
            tid, _ = self._ring.popitem(last=False)
            self._records.pop(tid, None)

    # ---------------------------------------------------------------- query

    def tree(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The assembled span forest for one trace id: fragments whose
        remote parent is present in another local fragment are grafted
        under it; the rest surface as roots (their parents live in
        another process — `scripts/trace_report.py` merges across
        endpoints)."""
        with self._lock:
            rec = self._records.get(trace_id)
            if rec is None:
                return None
            # Deep-copy: assemble_forest grafts fragments into each
            # other's children lists, which must not touch the store.
            fragments = copy.deepcopy(rec.fragments)
            out = {
                "trace_id": rec.trace_id,
                "route": rec.route,
                "flags": sorted(rec.flags),
                "duration_s": round(rec.duration_s, 6),
                "spans": assemble_forest(fragments),
            }
        return out

    def summaries(self, recent: int = 50) -> Dict[str, Any]:
        """The `/admin/trace` listing: pinned exemplars plus the most
        recent unpinned traces."""
        with self._lock:
            pinned = [r.summary() for r in self._records.values() if r.pins]
            pinned.sort(key=lambda s: -s["duration_s"])
            tail = [
                self._records[tid].summary()
                for tid in list(self._ring)[-recent:]
                if tid in self._records
            ]
        tail.reverse()
        return {"exemplars": pinned, "recent": tail}

    def records(self) -> List[Dict[str, Any]]:
        """Snapshot of every retained trace's assembled tree (the sim's
        per-stage breakdowns read this)."""
        with self._lock:
            ids = list(self._records)
        out = []
        for tid in ids:
            tree = self.tree(tid)
            if tree is not None:
                out.append(tree)
        return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._ring.clear()
            self._flagged.clear()
            self._slowest.clear()


def assemble_forest(
    fragments: Sequence[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Merge fragment dicts (possibly from several processes) into a
    forest: a fragment whose `parent_id` names a span inside another
    fragment is attached as that span's child; the rest stay roots.
    Pure-dict so `trace_report` can merge JSON fetched over HTTP."""
    index: Dict[str, Dict[str, Any]] = {}

    def walk(span: Dict[str, Any]) -> None:
        index[span["span_id"]] = span
        for c in span.get("children", ()):
            walk(c)

    for frag in fragments:
        walk(frag)
    roots: List[Dict[str, Any]] = []
    for frag in fragments:
        parent = index.get(frag.get("parent_id", ""))
        if parent is not None and parent is not frag:
            parent.setdefault("children", []).append(frag)
        else:
            roots.append(frag)
    roots.sort(key=lambda s: s.get("start_s", 0.0))
    return roots


def parse_trace_context(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """`"<trace_id>/<span_id>"` -> (trace_id, span_id); None if absent or
    malformed (a bad header must degrade to a fresh trace, never error)."""
    if not value or "/" not in value:
        return None
    trace_id, _, span_id = value.partition("/")
    if not trace_id or not span_id:
        return None
    return trace_id, span_id


# ------------------------------------------------------- process singleton

_tracer = Tracer()
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer every component shares (what makes the
    in-process sim cluster assemble complete cross-hop trees)."""
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the global tracer (tests; `configure()` for production)."""
    global _tracer
    with _tracer_lock:
        _tracer = tracer
    return tracer


def configure(
    *,
    enabled: bool = True,
    ring_size: int = 256,
    exemplars_per_route: int = 4,
    flagged_max: int = 64,
    max_spans_per_trace: int = 512,
) -> Tracer:
    """Rebuild the global tracer from `[tracing]` knobs (server entry
    points call this with the loaded config section)."""
    return set_tracer(Tracer(
        enabled=enabled, ring_size=ring_size,
        exemplars_per_route=exemplars_per_route, flagged_max=flagged_max,
        max_spans_per_trace=max_spans_per_trace,
    ))


def configure_from(cfg: Any) -> Tracer:
    """`configure()` from a config.TracingConfig (or anything shaped like
    one)."""
    return configure(
        enabled=cfg.enabled, ring_size=cfg.ring_size,
        exemplars_per_route=cfg.exemplars_per_route,
        flagged_max=cfg.flagged_max,
        max_spans_per_trace=cfg.max_spans_per_trace,
    )


# --------------------------------------------------------------- adapters


def trace_metadata(
    metadata: Optional[List[Tuple[str, str]]] = None,
) -> Optional[List[Tuple[str, str]]]:
    """Outgoing gRPC metadata with the current trace context appended —
    THE sanctioned shape for stub egress from request-path code (the
    `trace-propagation` lint rule requires every handler-reachable egress
    to build its metadata through this call). Returns None when there is
    neither base metadata nor an active span, matching gRPC's 'no
    metadata' convention."""
    header = get_tracer().context_header()
    if header is None:
        return metadata or None
    return list(metadata or []) + [header]


def traced_grpc_handler(name: str) -> Callable:
    """Decorator for async gRPC servicer methods: opens this process's
    fragment for the request (continuing the caller's trace context when
    present) for the duration of the handler."""

    def deco(fn: Callable) -> Callable:
        if inspect.isasyncgenfunction(fn):
            # Server-streaming handler: the span must stay open across
            # every yield (the fragment covers first chunk through final),
            # so the wrapper is itself an async generator.
            @functools.wraps(fn)
            async def gen_wrapper(self: Any, request: Any,
                                  context: Any) -> Any:
                with get_tracer().continue_from_grpc_context(context, name):
                    async for item in fn(self, request, context):
                        yield item

            return gen_wrapper

        @functools.wraps(fn)
        async def wrapper(self: Any, request: Any, context: Any) -> Any:
            with get_tracer().continue_from_grpc_context(context, name):
                return await fn(self, request, context)

        return wrapper

    return deco


def trace_admin_get(path: str) -> Dict[str, Any]:
    """The read-only trace endpoints, shared by every admin plane:

        GET /admin/trace           -> pinned exemplars + recent traces
        GET /admin/trace/<id>      -> the assembled span forest for <id>

    Raises KeyError for unknown paths/ids (the admin plane's 404)."""
    tracer = get_tracer()
    if path == "/admin/trace":
        return {"ok": True, **tracer.summaries()}
    prefix = "/admin/trace/"
    if path.startswith(prefix):
        tree = tracer.tree(path[len(prefix):])
        if tree is None:
            raise KeyError(path)
        return {"ok": True, "trace": tree}
    raise KeyError(path)
