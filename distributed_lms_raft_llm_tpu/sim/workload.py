"""Seeded deterministic workload generator: a semester of LMS traffic.

Simulated students and instructors, grouped into courses, issue the full
op mix — material upload/download, assignment submit, grading, instructor
Q&A, and on-/off-topic `ask_llm` (exercising the relevance gate and the
degraded fallback) — along a diurnal load curve compressed into the run's
wall-clock duration.

Determinism is the contract: the trace is a pure function of `SimConfig`
(seed included), pinned by `trace_digest` and the seeded-determinism test,
so a failed sim run replays from its seed. Arrivals come from a thinned
nonhomogeneous Poisson process (exponential gaps at the peak rate, each
arrival kept with probability rate(t)/peak) — the standard construction
that keeps the RNG stream independent of float drift in the rate curve.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import random
from typing import Dict, List, Tuple

from ..config import SimConfig

# Op kinds, student-issued unless noted.
ASK_LLM_SESSION_CHAIN = "ask_llm_session_chain"
DOWNLOAD_MATERIAL = "download_material"
SUBMIT_ASSIGNMENT = "submit_assignment"
ASK_LLM_ON_TOPIC = "ask_llm_on_topic"
ASK_LLM_OFF_TOPIC = "ask_llm_off_topic"
ASK_INSTRUCTOR = "ask_instructor"
CHECK_GRADE = "check_grade"
READ_RESPONSES = "read_responses"
UPLOAD_MATERIAL = "upload_material"    # instructor
GRADE = "grade"                        # instructor

# (kind, weight): the steady-state mix. ask_llm dominates (it is the
# product's hot path and the SLO target); a sprinkle of off-topic asks
# exercises the gate; reads interleave so read-your-writes is audited
# continuously, not only at the end.
OP_MIX: Tuple[Tuple[str, float], ...] = (
    (ASK_LLM_ON_TOPIC, 0.30),
    (ASK_LLM_OFF_TOPIC, 0.06),
    (DOWNLOAD_MATERIAL, 0.14),
    (SUBMIT_ASSIGNMENT, 0.10),
    (ASK_INSTRUCTOR, 0.08),
    (CHECK_GRADE, 0.10),
    (READ_RESPONSES, 0.07),
    (UPLOAD_MATERIAL, 0.08),
    (GRADE, 0.07),
)

ON_TOPIC_QUERIES = (
    "How does Raft elect a leader after a partition heals?",
    "Why does log matching guarantee state machine safety?",
    "When is an entry committed under a changing membership?",
    "How does a leadership transfer avoid a full election timeout?",
    "What makes InstallSnapshot safe for a lagging follower?",
)
OFF_TOPIC_QUERIES = (
    "What is the best pizza topping?",
    "Who won the world cup in 1998?",
    "Write me a poem about the sea.",
)
# Follow-up turns of a streamed tutoring session: each rides the SAME
# session id, so the server splices the prior turns' transcript as the
# shared prompt prefix (session-pinned radix blocks).
FOLLOWUP_QUERIES = (
    "Can you elaborate on that point?",
    "What happens in the failure case?",
    "How does that interact with snapshots?",
    "Give a concrete example of that.",
)
ASSIGNMENT_TEXT = (
    "Homework: explain the Raft consensus algorithm - leader election, "
    "log replication, commitment, safety under partitions, leadership "
    "transfer, and cluster membership changes."
)


@dataclasses.dataclass(frozen=True)
class SimOp:
    """One scheduled client operation."""

    at_s: float          # offset from workload start (wall seconds)
    actor: str           # username
    role: str            # "student" | "instructor"
    kind: str
    course: str
    payload: Dict[str, str]

    def key(self) -> str:
        """Canonical line for digests/diffs (payloads are str->str)."""
        items = ",".join(f"{k}={v}" for k, v in sorted(self.payload.items()))
        return (f"{self.at_s:.6f}|{self.actor}|{self.role}|{self.kind}|"
                f"{self.course}|{items}")


def trace_digest(ops: List[SimOp]) -> str:
    """Stable digest of a trace — the replay fingerprint the BENCH record
    carries and the seeded-determinism test pins."""
    h = hashlib.sha256()
    for op in ops:
        h.update(op.key().encode())
        h.update(b"\n")
    return h.hexdigest()[:16]


class WorkloadGenerator:
    """Pure function of the config: `ops()` returns the full trace."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.courses = [f"course{c}" for c in range(cfg.courses)]
        self.students = [f"student{i:03d}" for i in range(cfg.students)]
        self.instructors = [f"instructor{i}" for i in range(cfg.instructors)]

    def course_of(self, actor: str) -> str:
        """Static assignment: actors hash onto courses. With
        `course_concentration` > 0 the hash is skewed geometrically
        toward the first courses (1.0 = everyone on course0) — the
        same-course traffic regime the tutoring engine's shared-prefix
        KV cache targets. Still a pure function of the actor name, so
        the trace stays seed-deterministic."""
        h = int(hashlib.sha1(actor.encode()).hexdigest(), 16)
        c = self.cfg.course_concentration
        if c <= 0:
            return self.courses[h % len(self.courses)]
        weights = [(1.0 - c) ** i for i in range(len(self.courses))]
        u = (h % 10**9) / 10**9 * sum(weights)
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            if u < acc:
                return self.courses[i]
        return self.courses[-1]

    def course_context(self, course: str) -> str:
        """The deterministic course/assignment context on-topic asks are
        prefixed with under `course_concentration` > 0: every student in
        a course asks against the SAME context text, so their prompts
        share the token prefix the radix cache prefills once. Caveat at
        sim scale: the tiny tutoring model's position table is narrower
        than this context, and the engine keeps a prompt's TAIL — so in
        the tiny-paged soak the measured hits come from students
        repeating the same course question verbatim (still the radix
        partial-prefill path); genuine cross-question context sharing is
        exercised with token-level control by bench.py's shared-prefix
        scenario and tests/test_prefix_cache.py."""
        return (f"{course} assignment context: {ASSIGNMENT_TEXT} "
                f"Course question: ")

    def rate(self, t_s: float) -> float:
        """Diurnal ops/s at offset `t_s`: `days` sine cycles compressed
        into `duration_s`, trough at the start (campus asleep), peak at
        midday; never fully zero so the auditors always have traffic."""
        cfg = self.cfg
        phase = 2.0 * math.pi * (t_s / cfg.duration_s) * cfg.days
        diurnal = 1.0 + cfg.diurnal_amplitude * math.sin(phase - math.pi / 2)
        return cfg.base_rate * max(0.05, diurnal)

    def peak_rate(self) -> float:
        return self.cfg.base_rate * (1.0 + abs(self.cfg.diurnal_amplitude))

    def ops(self) -> List[SimOp]:
        cfg = self.cfg
        rng = random.Random(cfg.seed)
        kinds = [k for k, _ in OP_MIX]
        weights = [w for _, w in OP_MIX]
        ops: List[SimOp] = []
        counters = {"material": 0, "submit": 0}
        peak = self.peak_rate()
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= cfg.duration_s:
                break
            if rng.random() > self.rate(t) / peak:
                continue  # thinned: below the diurnal envelope right now
            kind = rng.choices(kinds, weights=weights, k=1)[0]
            ops.append(self._op(kind, t, rng, counters))
        ops.extend(self._session_chains())
        ops.sort(key=lambda op: (op.at_s, op.actor, op.kind))
        return ops

    def _session_chains(self) -> List[SimOp]:
        """Conversational follow-up chains: `session_fraction` of the
        students each run ONE multi-turn streamed session (one op — the
        executor drives the turns sequentially, since turn N+1 needs
        turn N's transcript on the server). A separate seeded RNG stream
        keeps the Poisson trace untouched by the chain knobs."""
        cfg = self.cfg
        n = min(len(self.students),
                round(cfg.session_fraction * len(self.students)))
        if n <= 0 or cfg.session_turns < 1:
            return []
        srng = random.Random(cfg.seed ^ 0x5E5510)
        chains: List[SimOp] = []
        for i in range(n):
            actor = self.students[i * len(self.students) // n]
            course = self.course_of(actor)
            first = srng.choice(ON_TOPIC_QUERIES)
            if cfg.course_concentration > 0:
                first = self.course_context(course) + first
            queries = [first] + [
                srng.choice(FOLLOWUP_QUERIES)
                for _ in range(cfg.session_turns - 1)
            ]
            # Chains start in the first 60% of the run so every turn —
            # each bounded by llm_budget_s — can finish inside it.
            at = srng.uniform(0.05, 0.60) * cfg.duration_s
            chains.append(SimOp(
                at_s=at, actor=actor, role="student",
                kind=ASK_LLM_SESSION_CHAIN, course=course,
                payload={"session": f"{actor}-chain{i}",
                         "queries": "\x1f".join(queries)},
            ))
        return chains

    # ------------------------------------------------------------ builders

    def _op(self, kind: str, t: float, rng: random.Random,
            counters: Dict[str, int]) -> SimOp:
        if kind in (UPLOAD_MATERIAL, GRADE):
            actor = rng.choice(self.instructors)
            role = "instructor"
        else:
            actor = rng.choice(self.students)
            role = "student"
        course = self.course_of(actor)
        payload: Dict[str, str] = {}
        if kind == UPLOAD_MATERIAL:
            counters["material"] += 1
            n = counters["material"]
            payload = {"filename": f"{course}_notes_{n:04d}.pdf",
                       "text": f"{course} lecture notes #{n}: "
                               f"{ASSIGNMENT_TEXT}"}
        elif kind == SUBMIT_ASSIGNMENT:
            counters["submit"] += 1
            payload = {"filename": f"{actor}_hw.pdf",
                       "text": f"{ASSIGNMENT_TEXT} (revision "
                               f"{counters['submit']:04d} by {actor})"}
        elif kind == ASK_LLM_ON_TOPIC:
            q = rng.choice(ON_TOPIC_QUERIES)
            if self.cfg.course_concentration > 0:
                # Shared course context: the prompt prefix is identical
                # for every on-topic ask in this course (off-topic asks
                # stay bare so the relevance gate keeps discriminating).
                q = self.course_context(course) + q
            payload = {"query": q}
        elif kind == ASK_LLM_OFF_TOPIC:
            payload = {"query": rng.choice(OFF_TOPIC_QUERIES)}
        elif kind == ASK_INSTRUCTOR:
            payload = {"query": f"{course}: please clarify point "
                                f"{rng.randrange(1, 9)} of the homework."}
        elif kind == GRADE:
            payload = {"student": rng.choice(self.students),
                       "grade": rng.choice(("A", "B", "C"))}
        # DOWNLOAD_MATERIAL / CHECK_GRADE / READ_RESPONSES carry no payload.
        return SimOp(at_s=t, actor=actor, role=role, kind=kind,
                     course=course, payload=payload)
