"""`SemesterSim`: workload + operations schedule + auditors, end to end.

One `run()` = one semester compressed into `duration_s` wall seconds:

1. boot the in-process cluster (`SimCluster`);
2. setup — register/login every actor, seed one material per course and
   one assignment per student (ask_llm requires one);
3. drive the seeded workload trace from `workers` client threads while
   the operations scheduler injects the event plan (chaos campaigns,
   TimeoutNow rolling restart, disk-fault quarantine, membership change)
   through the real admin plane;
4. settle — clear all faults, re-close every breaker by draining
   leadership to any node whose breaker is still open (the operator's
   decommission dance, automated), wait out storage recovery;
5. audit — a fresh client re-reads the world and the ledger proves zero
   acked-write loss; SLOs are evaluated from every node's `/metrics` and
   `/healthz`;
6. emit one BENCH-schema record (`scripts/semester_sim.py` prints it).

The trace and the event plan are pure functions of the seed; the record
carries their digests so a failure is replayable bit-for-bit at the
decision level.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import grpc

from ..client import LMSClient
from ..client.client import NoLeader
from ..config import SimConfig
from ..utils import locks
from ..utils import metrics_registry as metric
from ..utils import pdf
from ..utils.metrics import Metrics
from ..utils.resilience import DeadlineExpired
from ..utils.scrape import ClusterScraper, SourceFn, http_source
from ..utils.timeline import snap_counter, snap_hist
from ..utils.tracing import get_tracer
from . import events as ev
from . import workload as wl
from .cluster import SimCluster
from .slo import ContinuousSloEngine
from .ledger import (
    ASSIGNMENT,
    GRADE,
    MATERIAL,
    QUERY,
    USER,
    WriteLedger,
    content_hash,
)
from .slo import evaluate_slos

log = logging.getLogger(__name__)

class SimOpFailed(Exception):
    """A simulated op the cluster refused at the application level."""


_CLIENT_ERRORS = (grpc.RpcError, NoLeader, DeadlineExpired, TimeoutError,
                  SimOpFailed)


class _TelemetryLoop:
    """The sim's in-run telemetry plane: one thread polls every node's
    `/metrics` (plus the harness's own client-side Metrics and the
    in-process tutoring queue) through the REAL scrape aggregator into a
    merged cluster timeline, and runs the continuous SLO engine's
    burn-rate evaluation on each tick. Starts at workload t0, stops
    before settle — the settle phase's deliberate degraded probes must
    not read as alerts."""

    def __init__(self, sim: "SemesterSim", t0: float):
        self.sim = sim
        self.t0 = t0
        cluster = sim.cluster

        def sources() -> Dict[str, SourceFn]:
            # Re-resolved every poll: membership events change the node
            # set mid-run; a restarting node is simply unreachable for a
            # round.
            out: Dict[str, SourceFn] = {
                f"node{nid}": http_source(
                    f"http://127.0.0.1:{cluster.health_port(nid)}/metrics"
                )
                for nid in cluster.node_ids()
            }
            # "tutoring" is the merged fleet view (counters summed
            # across members — the capacity fit and degraded-rate burn
            # read it). Per-node fleet attribution lives in the BENCH
            # record's tutoring_fleet block, NOT as extra scrape
            # sources: feeding both the merged view and per-node views
            # into one ClusterScraper would double-count every tutoring
            # counter in the cluster timeline.
            out["tutoring"] = cluster.tutoring_metrics_snapshot
            out["sim"] = sim.metrics.snapshot
            return out

        self.scraper = ClusterScraper(sources_fn=sources)
        self.engine = ContinuousSloEngine(
            sim.cfg, self.scraper.cluster, sim.metrics,
            metrics=sim.metrics,
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="sim-telemetry", daemon=True
        )

    def start(self) -> "_TelemetryLoop":
        # Baseline poll BEFORE evaluations begin: the first sight of a
        # source seeds its counter baselines (boot-era counts must not
        # read as a rate spike in the first window).
        self.scraper.poll()
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("telemetry loop did not stop")

    def _run(self) -> None:
        while not self._stop.wait(self.sim.cfg.telemetry_sample_s):
            try:
                self.scraper.poll()
                self.engine.evaluate(time.monotonic() - self.t0)
            except Exception:
                # Telemetry must never kill the run it observes.
                log.exception("telemetry poll failed")


def _password(actor: str) -> str:
    return f"pw-{actor}"


def _is_degraded(resp) -> bool:
    # Match the degraded-answer sentinel exactly: a gate rejection also
    # mentions the instructor but queues NOTHING — counting it would
    # record a ledger write the cluster never committed, and the audit
    # would report a spurious acked-write loss.
    return bool(resp.success) and "forwarded to an instructor" in resp.response


class SemesterSim:
    def __init__(self, cfg: SimConfig, workdir: str):
        self.cfg = cfg
        self.workdir = workdir
        self.metrics = Metrics()
        self.ledger = WriteLedger(metrics=self.metrics)
        self.cluster = SimCluster(workdir, cfg)
        self.gen = wl.WorkloadGenerator(cfg)
        self._clients: Dict[str, LMSClient] = {}
        self._ops_bot: Optional[LMSClient] = None
        self._bot_lock = threading.Lock()
        self._bot_seq = 0

    # ------------------------------------------------------------------ run

    def run(self) -> Dict:
        t_start = time.monotonic()
        ops = self.gen.ops()
        plan = ev.plan_events(self.cfg)
        # Fresh flight recorder per run: the process-global tracer may
        # hold a previous run's traces (back-to-back sims in one test
        # process), which would pollute the per-stage p95s and could pin
        # a stale trace as this run's slowest exemplar.
        get_tracer().reset()
        # Live lock-order auditing across the whole run: every
        # OrderedLock acquisition in the in-process cluster lands in the
        # global acquisition graph; violations surface both through the
        # lock_order_violations counter and locks.violations(), and the
        # recorded graph stays readable after the run for the
        # static/dynamic cross-validation test.
        locks.reset()
        locks.set_metrics_sink(self.metrics)
        locks.enable_recording()
        try:
            # Inside the try: a partial boot (no leader within the
            # timeout, a stolen port) must still tear the cluster down,
            # or its loop thread and gRPC servers outlive the run.
            self.cluster.start()
            self._setup()
            scheduler = ev.OperationsScheduler(
                self.cluster, plan, metrics=self.metrics,
                writer=self._bot_write, asker=self._bot_ask,
                streamer=self._bot_stream, ledger=self.ledger,
            )
            t0 = time.monotonic()
            telemetry: Optional[_TelemetryLoop] = None
            if self.cfg.continuous_slos:
                telemetry = _TelemetryLoop(self, t0).start()
            threads = self._start_workers(ops, t0)
            scheduler.start(t0)
            margin = 30.0 + self.cfg.llm_budget_s
            for t in threads:
                t.join(self.cfg.duration_s + margin)
                if t.is_alive():
                    raise TimeoutError(f"sim worker {t.name} wedged")
            scheduler.join(self.cfg.duration_s + margin)
            if telemetry is not None:
                # Stop BEFORE settle: the settle phase's deliberate
                # degraded probes are post-scenario housekeeping, not
                # SLO evidence.
                telemetry.stop()
                telemetry.engine.finish(scheduler.event_windows())
            self._settle()
            self._audit()
            # After the audit (its logins are themselves replicated
            # writes): wait for every group's replicas to drain to one
            # applied index and compare their state-digest chains.
            self.ledger.note_replica_digests(
                self._collect_replica_digests()
            )
            node_metrics, node_health = self.cluster.scrape_all()
            traces = get_tracer().records()
            fleet = self._fleet_summary(node_metrics, node_health)
            scoring = self._scoring_summary()
            groups = self._groups_summary()
            report = evaluate_slos(
                self.cfg, node_metrics, node_health,
                self.metrics.snapshot(), self.ledger.report(),
                event_failures=scheduler.failures(),
                traces=traces,
                tutoring_metrics=self.cluster.tutoring_metrics_snapshot(),
                metrics=self.metrics,
                continuous=(telemetry.engine.report()
                            if telemetry is not None else None),
                fleet=fleet,
                scoring=scoring,
                groups=groups,
            )
            return self._record(ops, plan, scheduler, report, node_metrics,
                                traces, time.monotonic() - t_start,
                                telemetry=telemetry, fleet=fleet,
                                scoring=scoring, groups=groups)
        finally:
            for c in self._clients.values():
                c.close()
            if self._ops_bot is not None:
                self._ops_bot.close()
            self.cluster.stop()
            locks.disable_recording()
            locks.set_metrics_sink(None)

    # ---------------------------------------------------------------- setup

    def _new_client(self, actor: str,
                    request_timeout_s: float = 15.0) -> LMSClient:
        return LMSClient(
            self.cluster.client_servers(),
            # Sharded runs: clients key their leader-hint cache by Raft
            # group (the static lane from the initial map), so evicting
            # one group's distrusted hint leaves the others' warm.
            group_of=(self.cluster.group_of
                      if self.cfg.lms_groups > 1 else None),
            discovery_rounds=8, discovery_backoff_s=0.2,
            rpc_retries=6, rpc_timeout=5.0,
            request_timeout_s=request_timeout_s,
            llm_timeout_s=self.cfg.llm_budget_s,
            backoff_base_s=0.02, backoff_max_s=0.3,
            # Stable hash, NOT builtin hash(): PYTHONHASHSEED randomizes
            # the latter per process, which would give every replay a
            # different backoff-jitter stream and break the
            # replay-from-seed contract.
            seed=int(hashlib.sha1(
                f"{self.cfg.seed}:{actor}".encode()
            ).hexdigest(), 16) & 0xFFFF,
        )

    def _setup(self) -> None:
        """Accounts + seed content, before the clock starts. Setup runs
        fault-free, so failures here are raised, not tolerated."""
        actors: List[Tuple[str, str]] = (
            [(s, "student") for s in self.gen.students]
            + [(i, "instructor") for i in self.gen.instructors]
        )

        errors: List[str] = []

        def boot_actor(actor: str, role: str) -> None:
            # Setup runs fault-free but NOT contention-free: at soak
            # scale, dozens of concurrent account boots can push an
            # attempt past its budget — retry the whole actor rather
            # than fail the run before the scenario even starts.
            last: Optional[Exception] = None
            for _ in range(3):
                try:
                    c = self._clients.get(actor) or self._new_client(
                        actor, request_timeout_s=30.0
                    )
                    self._clients[actor] = c
                    c.register(actor, _password(actor), role)
                    if not c.login(actor, _password(actor)):
                        raise RuntimeError(
                            f"setup: login failed for {actor}"
                        )
                    # Login success proves the account committed
                    # (register alone can report 'exists' on a
                    # retried-but-committed proposal).
                    self.ledger.record(USER, (actor,), role)
                    if role == "student":
                        filename = f"{actor}_hw.pdf"
                        data = pdf.make_pdf(
                            f"{wl.ASSIGNMENT_TEXT} (initial submission "
                            f"by {actor})"
                        )
                        if not c.upload_assignment(filename, data):
                            raise RuntimeError(
                                f"setup: upload failed for {actor}"
                            )
                        self.ledger.record(ASSIGNMENT, (actor, filename),
                                           content_hash(data))
                    return
                except Exception as e:
                    last = e
                    time.sleep(0.5)
            errors.append(f"{actor}: {last}")

        def reap(t: threading.Thread) -> None:
            t.join(60.0)
            if t.is_alive():
                # An abandoned boot thread would race the workload phase
                # on its (shared, single-threaded-by-design) client —
                # fail setup loudly instead.
                errors.append(f"{t.name}: still running after 60s join")

        threads = [threading.Thread(target=boot_actor, args=a,
                                    name=f"setup-{a[0]}", daemon=True)
                   for a in actors]
        alive: List[threading.Thread] = []
        for t in threads:
            t.start()
            alive.append(t)
            if len(alive) >= self.cfg.workers:
                reap(alive.pop(0))
        for t in alive:
            reap(t)
        if errors:
            raise RuntimeError(f"setup failed: {errors}")
        # One seed material per course so downloads never start empty.
        instructor = self.gen.instructors[0]
        for course in self.gen.courses:
            filename = f"{course}_syllabus.pdf"
            data = pdf.make_pdf(f"{course} syllabus: {wl.ASSIGNMENT_TEXT}")
            if not self._clients[instructor].upload_course_material(
                filename, data
            ):
                raise RuntimeError(f"setup: material failed for {course}")
            self.ledger.record(MATERIAL, (filename,), content_hash(data))
        # The scheduler's ops bot: guaranteed-traffic writer + degraded
        #-path prober (a student, so it can ask_llm).
        bot = self._new_client("ops_bot")
        bot.register("ops_bot", _password("ops_bot"), "student")
        if not bot.login("ops_bot", _password("ops_bot")):
            raise RuntimeError("setup: ops bot login failed")
        self.ledger.record(USER, ("ops_bot",), "student")
        data = pdf.make_pdf("ops bot assignment")
        if not bot.upload_assignment("ops_bot_hw.pdf", data):
            raise RuntimeError("setup: ops bot upload failed")
        self.ledger.record(ASSIGNMENT, ("ops_bot", "ops_bot_hw.pdf"),
                           content_hash(data))
        self._ops_bot = bot

    # ----------------------------------------------------------- scheduler IO

    def _group_tag(self, actor: str):
        """The actor's owning Raft group per the LIVE routing map (None
        in single-group runs): stamped on every acked write so the
        audit can name the writes that crossed a resharding boundary."""
        if self.cfg.lms_groups <= 1:
            return None
        return self.cluster.live_group_of(actor)

    def _bot_write(self) -> bool:
        """One guaranteed acked write (the quarantine event's record
        source); ledger-tracked like any student write."""
        with self._bot_lock:
            self._bot_seq += 1
            seq = self._bot_seq
        query = f"ops bot write #{seq:04d}"
        try:
            if self._ops_bot.ask_instructor(query):
                self.ledger.record(QUERY, ("ops_bot",), query,
                                   group=self._group_tag("ops_bot"))
                return True
        except _CLIENT_ERRORS as e:
            log.info("ops bot write failed: %s", e)
        return False

    def _bot_ask(self) -> bool:
        """One ask_llm probe (the fleet drills resolve THIS query's
        affinity node and fault it, so the probe's hedge/spill is
        guaranteed to exercise the router); True if answered degraded."""
        try:
            resp = self._ops_bot.ask_llm(ev.PROBE_QUERY, budget_s=4.0)
        except _CLIENT_ERRORS as e:
            log.info("ops bot ask failed: %s", e)
            return False
        if _is_degraded(resp):
            self.metrics.inc(metric.SIM_DEGRADED_ANSWERS)
            self.ledger.record(QUERY, ("ops_bot",), ev.PROBE_QUERY,
                               group=self._group_tag("ops_bot"))
            return True
        return False

    def _bot_stream(self) -> bool:
        """One STREAMED ask_llm probe riding a fixed session id (the
        stream-kill drill faults this session's affinity node mid-answer,
        so the probe's resume-at-offset failover is guaranteed to
        exercise the router); True if the stream completed with its
        digest intact."""
        try:
            ans = self._ops_bot.ask_llm_stream(
                ev.PROBE_QUERY, session_id=ev.STREAM_SESSION_ID,
                budget_s=4.0,
            )
        except _CLIENT_ERRORS as e:
            log.info("ops bot stream failed: %s", e)
            return False
        if ans.resumes:
            self.metrics.inc(metric.SIM_STREAM_RESUMES, ans.resumes)
        if ans.digest_ok is False:
            self.metrics.inc(metric.SIM_STREAM_DIGEST_MISMATCH)
            return False
        if _is_degraded(ans):
            self.metrics.inc(metric.SIM_DEGRADED_ANSWERS)
            self.ledger.record(QUERY, ("ops_bot",), ev.PROBE_QUERY,
                               group=self._group_tag("ops_bot"))
            return False
        return bool(ans.success)

    # -------------------------------------------------------------- workload

    def _start_workers(self, ops: List[wl.SimOp],
                       t0: float) -> List[threading.Thread]:
        # Partition by actor so each client (one token, one channel set)
        # stays single-threaded; ops per actor run in trace order.
        buckets: List[List[wl.SimOp]] = [[] for _ in range(self.cfg.workers)]
        actor_ids = {a: i for i, a in enumerate(
            self.gen.students + self.gen.instructors
        )}
        for op in ops:
            buckets[actor_ids[op.actor] % self.cfg.workers].append(op)
        threads = []
        for w, bucket in enumerate(buckets):
            t = threading.Thread(
                target=self._worker, args=(bucket, t0),
                name=f"sim-worker-{w}", daemon=True,
            )
            t.start()
            threads.append(t)
        return threads

    def _worker(self, bucket: List[wl.SimOp], t0: float) -> None:
        # Closed-loop overload shedding: a worker that falls further
        # behind the trace than its own op budget sheds the late op
        # instead of building an unbounded backlog (which would wedge the
        # run long past its duration when the engine is the bottleneck).
        late_drop_s = self.cfg.llm_budget_s
        for op in bucket:
            delay = t0 + op.at_s - time.monotonic()
            if delay < -late_drop_s:
                self.metrics.inc(metric.SIM_OPS_DROPPED)
                continue
            if delay > 0:
                time.sleep(delay)
            started = time.monotonic()
            try:
                self._execute(op)
                self.metrics.inc(metric.SIM_OPS_OK)
            except _CLIENT_ERRORS as e:
                # Terminal client failure (budget + retries exhausted):
                # legal under faults — the op was never acked, so the
                # ledger expects nothing from it.
                log.info("sim op %s by %s failed: %s", op.kind, op.actor, e)
                self.metrics.inc(metric.SIM_OPS_FAILED)
            except Exception:
                # A harness bug must not silently kill the worker thread
                # (and every later op in its bucket) — count and carry on.
                log.exception("sim op %s by %s raised unexpectedly",
                              op.kind, op.actor)
                self.metrics.inc(metric.SIM_OPS_FAILED)
            finally:
                self.metrics.hist(metric.SIM_OP_LATENCY).observe(
                    time.monotonic() - started
                )

    def _execute(self, op: wl.SimOp) -> None:
        c = self._clients[op.actor]
        kind, payload = op.kind, op.payload
        if kind == wl.UPLOAD_MATERIAL:
            data = pdf.make_pdf(payload["text"])
            if c.upload_course_material(payload["filename"], data):
                self.ledger.record(MATERIAL, (payload["filename"],),
                                   content_hash(data),
                                   group=self._group_tag(op.actor))
        elif kind == wl.SUBMIT_ASSIGNMENT:
            data = pdf.make_pdf(payload["text"])
            if c.upload_assignment(payload["filename"], data):
                self.ledger.record(ASSIGNMENT, (op.actor,
                                                payload["filename"]),
                                   content_hash(data),
                                   group=self._group_tag(op.actor))
        elif kind == wl.GRADE:
            resp = c.grade(payload["student"], payload["grade"])
            if resp.success:
                self.ledger.record(GRADE, (payload["student"],),
                                   payload["grade"],
                                   group=self._group_tag(
                                       payload["student"]))
        elif kind == wl.ASK_INSTRUCTOR:
            if c.ask_instructor(payload["query"]):
                self.ledger.record(QUERY, (op.actor,), payload["query"],
                                   group=self._group_tag(op.actor))
        elif kind in (wl.ASK_LLM_ON_TOPIC, wl.ASK_LLM_OFF_TOPIC):
            t1 = time.monotonic()
            try:
                resp = c.ask_llm(payload["query"],
                                 budget_s=self.cfg.llm_budget_s)
            finally:
                self.metrics.hist(metric.SIM_ASK_LATENCY).observe(
                    time.monotonic() - t1
                )
            if _is_degraded(resp):
                # The degraded path IS a write: the query went onto the
                # replicated instructor queue — hold the cluster to it.
                self.metrics.inc(metric.SIM_DEGRADED_ANSWERS)
                self.ledger.record(QUERY, (op.actor,), payload["query"],
                                   group=self._group_tag(op.actor))
            elif not resp.success:
                raise SimOpFailed(f"ask_llm refused: {resp.response[:80]}")
        elif kind == wl.ASK_LLM_SESSION_CHAIN:
            self._run_session_chain(c, op)
        elif kind == wl.DOWNLOAD_MATERIAL:
            t1 = time.monotonic()
            entries = c.course_materials()
            self.ledger.check_materials_read(
                t1, {e.filename: bytes(e.file) for e in entries}, op.actor
            )
        elif kind == wl.CHECK_GRADE:
            t1 = time.monotonic()
            shown = c.my_grade()
            self.ledger.check_grade_read(t1, shown, op.actor)
        elif kind == wl.READ_RESPONSES:
            t1 = time.monotonic()
            texts = [e.data for e in c.instructor_responses()]
            self.ledger.check_responses_read(t1, texts, op.actor)
        else:  # pragma: no cover - generator and executor share the enum
            raise ValueError(f"unknown op kind {kind!r}")

    def _run_session_chain(self, c: LMSClient, op: wl.SimOp) -> None:
        """One conversational session, end to end: every turn streams
        over the SAME session id (sticky affinity, transcript splice on
        the serving node), TTFT is recorded per turn, and the final
        chunk's digest check catches any duplicated/dropped token. A
        terminally failed turn abandons the rest of the chain — later
        turns converse against the transcript the failed turn never
        produced."""
        sid = op.payload["session"]
        for turn, query in enumerate(op.payload["queries"].split("\x1f"),
                                     start=1):
            t1 = time.monotonic()
            try:
                ans = c.ask_llm_stream(query, session_id=sid,
                                       budget_s=self.cfg.llm_budget_s)
            except _CLIENT_ERRORS as e:
                log.info("session %s turn %d failed: %s", sid, turn, e)
                self.metrics.inc(metric.SIM_SESSION_TURNS_FAILED)
                return
            finally:
                self.metrics.hist(metric.SIM_ASK_LATENCY).observe(
                    time.monotonic() - t1
                )
            self.metrics.inc(metric.SIM_SESSION_TURNS)
            if ans.ttft_s is not None:
                self.metrics.hist(metric.SIM_TURN_TTFT).observe(ans.ttft_s)
            if ans.resumes:
                self.metrics.inc(metric.SIM_STREAM_RESUMES, ans.resumes)
            if ans.digest_ok is False:
                self.metrics.inc(metric.SIM_STREAM_DIGEST_MISMATCH)
            if _is_degraded(ans):
                # Same contract as the unary path: a degraded answer IS
                # a write onto the replicated instructor queue.
                self.metrics.inc(metric.SIM_DEGRADED_ANSWERS)
                self.ledger.record(QUERY, (op.actor,), query,
                                   group=self._group_tag(op.actor))
            elif not ans.success:
                raise SimOpFailed(
                    f"session turn refused: {ans.response[:80]}"
                )

    # ---------------------------------------------------------------- settle

    def _settle(self) -> None:
        """Back to blue skies: clear every fault, then re-close every
        breaker. A breaker only sees traffic while its node leads, so a
        node that led through the tutoring blackout and then lost
        leadership would hold an open breaker forever; the settle drains
        leadership to each such node and probes until it closes — the
        automated version of an operator's post-incident checklist."""
        for nid in self.cluster.node_ids():
            self.cluster.admin_post(nid, "/admin/faults", {"reset": True})
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            leader = self.cluster.wait_leader(timeout=10.0)
            if leader is None:
                continue
            open_nodes = [
                nid for nid in self.cluster.node_ids()
                if self.cluster.healthz(nid)
                .get("tutoring_breaker", {}).get("state") != "closed"
            ]
            if not open_nodes:
                return
            target = open_nodes[0]
            if target != leader:
                try:
                    self.cluster.admin_post(leader, "/admin/transfer",
                                            {"target": target})
                except RuntimeError as e:
                    log.info("settle transfer to %d failed: %s", target, e)
                    continue
            # recovery_s is 0.5 in the sim cluster: give the breaker its
            # half-open window, then probe until a success closes it.
            time.sleep(0.6)
            try:
                # "ops bot ..." overlaps the bot's assignment text, so the
                # probe passes the relevance gate and reaches tutoring —
                # a gated-out probe could never close the breaker.
                resp = self._ops_bot.ask_llm("ops bot settle probe?",
                                             budget_s=4.0)
                if _is_degraded(resp):
                    self.metrics.inc(metric.SIM_DEGRADED_ANSWERS)
                    self.ledger.record(QUERY, ("ops_bot",),
                                       "ops bot settle probe?")
            except _CLIENT_ERRORS as e:
                log.info("settle probe failed: %s", e)
        raise TimeoutError("settle: breakers never re-closed")

    # ----------------------------------------------------------------- audit

    def _audit(self) -> None:
        """Fresh reads of the final state feed the ledger's loss audit."""
        auditor = self._new_client("auditor")
        try:
            users: Dict[str, str] = {}
            for actor, role in (
                [(s, "student") for s in self.gen.students]
                + [(i, "instructor") for i in self.gen.instructors]
                + [("ops_bot", "student")]
            ):
                try:
                    if auditor.login(actor, _password(actor)):
                        users[actor] = role
                except _CLIENT_ERRORS:
                    pass
            # Materials: any student's view (reads are linearizable).
            student = self.gen.students[0]
            if not auditor.login(student, _password(student)):
                raise RuntimeError("audit: student login failed")
            materials = {e.filename: bytes(e.file)
                         for e in auditor.course_materials()}
            grades: Dict[str, str] = {}
            for s in self.gen.students:
                if auditor.login(s, _password(s)):
                    grades[s] = auditor.my_grade()
            instructor = self.gen.instructors[0]
            if not auditor.login(instructor, _password(instructor)):
                raise RuntimeError("audit: instructor login failed")
            assignments: Dict[str, List[str]] = {}
            for e in auditor.student_assignments():
                assignments.setdefault(e.id, []).append(e.filename)
            queries = [(e.id, e.data)
                       for e in auditor.unanswered_queries()]
            self.ledger.audit(users=users, materials=materials,
                              assignments=assignments, grades=grades,
                              queries=queries)
        finally:
            auditor.close()

    # ---------------------------------------------------------------- record

    def _fleet_summary(self, node_metrics: Dict, node_health: Dict):
        """Tutoring-fleet verdict inputs: router counters summed across
        the LMS nodes (whichever node led during a drill holds them)
        plus the end-state per-node routing map. None for a one-node
        fleet — the checks and record fields only exist when there is a
        fleet to judge."""
        if self.cluster.tutoring_count() <= 1:
            return None

        def total(name: str) -> int:
            return sum(snap_counter(s, name)
                       for s in node_metrics.values())

        nodes = []
        for health in node_health.values():
            fleet = health.get("tutoring_fleet") or {}
            if fleet.get("nodes"):
                nodes = fleet["nodes"]
                break
        return {
            "size": self.cluster.tutoring_count(),
            "drills": self.cfg.events,
            "spills": total(metric.TUTORING_SPILLS),
            "hedges": total(metric.TUTORING_HEDGES),
            "hedge_wins": total(metric.TUTORING_HEDGE_WINS),
            "ejections": total(metric.TUTORING_NODE_EJECTIONS),
            "rejoins": total(metric.TUTORING_NODE_REJOINS),
            # Resumable-stream evidence: router-side resume-at-offset
            # failovers and per-chunk stall trips (the stream-kill drill
            # must leave >= 1 resume behind).
            "stream_resumes": total(metric.STREAM_RESUMES),
            "stream_stalls": total(metric.STREAM_STALLS),
            "nodes": nodes,
        }

    def _collect_replica_digests(self) -> Optional[Dict]:
        """Cross-replica convergence audit at settle (replicas_converged
        SLO): each physical node reports, per Raft group, its replica's
        digest chain (GET /admin/raft -> digest / digest_applied). A
        group converged when every responding replica sits at the SAME
        applied index with the SAME digest — including across a mid-run
        group split, whose InstallSnapshot-restored members must resume
        the source chain, not fork it. Replicas drain asynchronously, so
        poll briefly before judging; unreachable nodes are skipped (a
        node the drill killed proves nothing about determinism)."""
        if self.cfg.lms_groups <= 1:
            return None
        deadline = time.monotonic() + 15.0
        doc: Dict = {"converged": False, "groups": {}}
        while True:
            per_group: Dict[str, Dict[str, Dict]] = {}
            for nid in self.cluster.node_ids():
                try:
                    topo = self.cluster.group_topology(nid)
                except (RuntimeError, OSError):
                    continue
                for gid, row in (topo.get("groups") or {}).items():
                    if "digest" not in row:
                        continue
                    per_group.setdefault(gid, {})[str(nid)] = {
                        "applied": row.get("digest_applied"),
                        "digest": row.get("digest"),
                    }
            converged = bool(per_group)
            for rows in per_group.values():
                if len(rows) < 2:
                    converged = False  # one report compares nothing
                    continue
                if len({r["applied"] for r in rows.values()}) != 1:
                    converged = False  # still draining (or wedged)
                elif len({r["digest"] for r in rows.values()}) != 1:
                    converged = False  # SAME index, DIFFERENT state
            doc = {"converged": converged, "groups": per_group}
            if converged or time.monotonic() > deadline:
                return doc
            time.sleep(0.3)

    def _groups_summary(self) -> Optional[Dict]:
        """Sharded-control-plane verdict inputs: the final routing map
        and per-group topology (GET /admin/raft), per-group leaders from
        the cluster's live records, and the ledger's reshard-boundary
        evidence. None for a single-group run — the checks and record
        fields only exist when there are groups to judge."""
        if self.cfg.lms_groups <= 1:
            return None
        nid = (self.cluster.wait_leader(timeout=10.0)
               or self.cluster.node_ids()[0])
        topo = self.cluster.group_topology(nid)
        leaders = {gid: self.cluster.group_leader(gid)
                   for gid in range(self.cfg.lms_groups)}
        ledger_report = self.ledger.report()
        return {
            "n_groups": self.cfg.lms_groups,
            "routing_map": topo.get("routing_map", {}),
            "topology": topo.get("groups", {}),
            "leaders": leaders,
            # The verdict only DEMANDS a completed handoff when the
            # event schedule actually planned the live split.
            "expected_reshard": bool(self.cfg.events),
            "reshards": ledger_report.get("reshards", []),
            "acked_by_group": ledger_report.get("acked_by_group", {}),
            "acked_across_reshard": ledger_report.get(
                "acked_across_reshard", 0
            ),
            "replica_digests": ledger_report.get("replica_digests"),
        }

    def _scoring_summary(self) -> Optional[Dict]:
        """Background scoring-tenant evidence from the tutoring fleet's
        merged counters: the bulk-grading night's completion claim
        (`bulk_scoring_completed`) and the record's idle-lane-harvest
        block. None when [sim] bulk_scoring is off."""
        if not self.cfg.bulk_scoring:
            return None
        tut = self.cluster.tutoring_metrics_snapshot()
        return {
            # The verdict only DEMANDS a completed job when the event
            # schedule actually ran the bulk-grading night.
            "expected": bool(self.cfg.events),
            "jobs_completed": snap_counter(
                tut, metric.SCORING_JOBS_COMPLETED
            ),
            "jobs_failed": snap_counter(tut, metric.SCORING_JOBS_FAILED),
            "quanta": snap_counter(tut, metric.SCORING_QUANTA),
            "scored_tokens": snap_counter(
                tut, metric.SCORING_SCORED_TOKENS
            ),
            "truncated_texts": snap_counter(
                tut, metric.SCORE_TRUNCATED_TEXTS
            ),
            "preempt_wait_ms": snap_counter(
                tut, metric.SCORE_PREEMPT_WAIT_MS
            ),
        }

    def _record(self, ops, plan, scheduler, report, node_metrics,
                traces, wall_s: float, telemetry=None,
                fleet=None, scoring=None, groups=None) -> Dict:
        snap = self.metrics.snapshot()
        counters = snap.get("counters", {})
        ask = snap_hist(snap, metric.SIM_ASK_LATENCY)
        ledger_report = self.ledger.report()

        def node_sum(name: str) -> int:
            # Undercounts across a rolling restart (the restarted node's
            # counters reset) — good enough for ">= 1 really happened".
            return sum(snap_counter(s, name)
                       for s in node_metrics.values())

        gate_pass = node_sum(metric.GATE_PASS)
        gate_reject = node_sum(metric.GATE_REJECT)

        # The flight recorder's verdict attachments: exemplar digests
        # (what was pinned and why — slow, degraded, errored) and the
        # slowest ask's FULL span tree, so a perf regression's BENCH line
        # carries its own waterfall (`scripts/trace_report.py --json`)
        # instead of sending the reader off to rerun the sim.
        exemplars = [
            {"trace_id": t["trace_id"], "route": t["route"],
             "duration_s": t["duration_s"], "flags": t["flags"]}
            for t in sorted(traces, key=lambda t: -t["duration_s"])
            if t.get("flags")
            or t["route"].startswith("client.ask_llm")
        ][:8]
        asks = [t for t in traces if t["route"] == "client.ask_llm"]
        slowest = max(asks, key=lambda t: t["duration_s"], default=None)
        return {
            # BENCH schema: one headline metric + the full story around it.
            "metric": "semester_sim_ask_p95_s",
            "value": round(float(ask.get("p95_s", 0.0)), 3),
            "unit": "s",
            "seed": self.cfg.seed,
            "students": self.cfg.students,
            "duration_s": self.cfg.duration_s,
            "tutoring_engine": self.cfg.tutoring_engine,
            "tutoring_nodes": self.cfg.tutoring_nodes,
            # Fleet router outcome (None for a one-node fleet): spill /
            # hedge / ejection counts plus the end-state routing map —
            # the acceptance evidence for the kill-one-of-N and
            # drain-and-rejoin drills.
            "tutoring_fleet": fleet,
            # Idle-lane harvest evidence (None when [sim] bulk_scoring is
            # off): the bulk-grading night's jobs/quanta/tokens plus the
            # measured interactive preemption wait behind score quanta.
            "scoring": scoring,
            # Sharded-control-plane evidence (None for one group): the
            # final routing map, per-group leaders, and which acked
            # writes crossed the live split's resharding boundary.
            "lms_groups": self.cfg.lms_groups,
            "groups": groups,
            "course_concentration": self.cfg.course_concentration,
            # Measured shared-prefix KV cache hit rate on the tutoring
            # node (None unless the engine runs the radix cache, i.e.
            # tutoring_engine = "tiny-paged").
            "prefix_cache_hit_rate": report.prefix_cache_hit_rate,
            "trace_digest": wl.trace_digest(ops),
            "event_digest": _event_digest(plan),
            "ops_planned": len(ops),
            "ops_ok": counters.get("sim_ops_ok", 0),
            "ops_failed": counters.get("sim_ops_failed", 0),
            "ops_dropped": counters.get("sim_ops_dropped", 0),
            "asks": ask.get("count", 0),
            # Conversational/streaming evidence: completed streamed
            # turns, their TTFT distribution, client-observed
            # resume-at-offset failovers, and digest mismatches (must be
            # 0 — also a verdict check).
            "sessions": {
                "turns_ok": counters.get("sim_session_turns", 0),
                "turns_failed": counters.get("sim_session_turns_failed",
                                             0),
                "turn_ttft": snap_hist(snap, metric.SIM_TURN_TTFT),
                "stream_resumes": counters.get("sim_stream_resumes", 0),
                "digest_mismatches": counters.get(
                    "sim_stream_digest_mismatch", 0
                ),
            },
            "degraded_answers": counters.get("sim_degraded_answers", 0),
            "gate_pass": gate_pass,
            "gate_reject": gate_reject,
            "acked_writes": ledger_report["acked_writes"],
            "events": scheduler.outcomes,
            "events_executed": scheduler.executed_kinds(),
            "slos": report.to_dict(),
            "trace_exemplars": exemplars,
            "slowest_trace": slowest,
            # The in-run telemetry plane's artifacts: the burn-rate
            # engine's report (also inside slos.continuous) and the full
            # scraped timeline export — the input
            # `scripts/telemetry.py --capacity` fits the capacity model
            # over, embedded so one BENCH line replays the analysis.
            "telemetry": (telemetry.engine.report()
                          if telemetry is not None else None),
            "timeline": (telemetry.scraper.export()
                         if telemetry is not None else None),
            "wall_s": round(wall_s, 1),
        }


def _event_digest(plan: List[ev.SimEvent]) -> str:
    h = hashlib.sha256()
    for e in plan:
        h.update(e.key().encode())
        h.update(b"\n")
    return h.hexdigest()[:16]
