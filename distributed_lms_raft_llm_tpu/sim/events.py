"""The operations schedule: planned and unplanned events injected mid-run.

`plan_events` is — like the workload trace — a pure function of the
config: the schedule (what happens, when, with which parameters) comes
from the seed, so a failed run replays. `OperationsScheduler` executes
the plan against a live `SimCluster` strictly through the surfaces real
operators use: `POST /admin/faults` (including timed campaigns),
`POST /admin/transfer` (TimeoutNow leadership handoff), the disk-fault
admin plane for the storage-recovery quarantine, and
`POST /admin/membership` for the add/remove — then verifies each event's
observable outcome from `/healthz` (`GET /admin/faults` for campaigns).

Each event records an outcome dict; any `ok=False` outcome fails the
run's verdict (the harness feeds `failures()` into `evaluate_slos` as
the `events_completed` check), so the acceptance criteria —
>=1 transfer, >=1 quarantine+rejoin, >=1 membership change — are proven,
not assumed.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Dict, List, Optional

from ..config import SimConfig
from ..utils import metrics_registry as metric

log = logging.getLogger(__name__)

CHAOS_CAMPAIGN = "chaos_campaign"
ROLLING_RESTART = "rolling_restart"
QUARANTINE = "quarantine"
MEMBERSHIP_ADD = "membership_add"
MEMBERSHIP_REMOVE = "membership_remove"


@dataclasses.dataclass(frozen=True)
class SimEvent:
    at_s: float      # offset from workload start
    kind: str
    params: Dict[str, float]

    def key(self) -> str:
        items = ",".join(f"{k}={v}" for k, v in sorted(self.params.items()))
        return f"{self.at_s:.6f}|{self.kind}|{items}"


def _jitter(rng: random.Random, frac: float, width: float) -> float:
    return frac + rng.uniform(-width, width)


def plan_events(cfg: SimConfig) -> List[SimEvent]:
    """The semester's operations calendar, scaled to `duration_s`.

    Layout (fractions of the run, seed-jittered): an early network-chaos
    campaign whose last phase blacks out the tutoring hop (degraded
    answers, breaker open/close), a rolling restart of the leader via
    TimeoutNow transfer, a follower quarantined into storage recovery via
    disk bit flips, then a membership add and the matching remove.
    """
    if not cfg.events:
        return []
    rng = random.Random(cfg.seed ^ 0x5EED)
    T = cfg.duration_s
    chaos_hold = max(1.0, 0.10 * T)
    # The blackout must outlast the continuous SLO engine's fast window
    # (max(1.0, 0.06*T)) with margin: the fast-window burn alert needs a
    # span where the window sits fully inside the outage, plus the
    # sustain requirement — a blackout shorter than the window can only
    # ever produce diluted ratios.
    outage_hold = max(1.5, 0.08 * T)
    return [
        SimEvent(
            at_s=_jitter(rng, 0.12, 0.02) * T, kind=CHAOS_CAMPAIGN,
            params={
                "drop": 0.10, "delay_s": 0.002, "delay_jitter_s": 0.01,
                "duplicate": 0.05, "hold_s": round(chaos_hold, 3),
                "outage_hold_s": round(outage_hold, 3),
            },
        ),
        SimEvent(at_s=_jitter(rng, 0.38, 0.02) * T, kind=ROLLING_RESTART,
                 params={}),
        SimEvent(
            at_s=_jitter(rng, 0.55, 0.02) * T, kind=QUARANTINE,
            params={"burst_s": round(max(0.8, 0.05 * T), 3),
                    "settle_s": round(max(0.6, 0.03 * T), 3)},
        ),
        SimEvent(at_s=_jitter(rng, 0.75, 0.02) * T, kind=MEMBERSHIP_ADD,
                 params={}),
        SimEvent(at_s=_jitter(rng, 0.90, 0.02) * T, kind=MEMBERSHIP_REMOVE,
                 params={}),
    ]


class OperationsScheduler:
    """Executes a plan against a `SimCluster` on its own thread.

    `writer` is a callable issuing one guaranteed acked write (the
    harness's ops-bot client): the quarantine event uses it to make sure
    corrupted-on-disk records actually exist during the bit-flip burst
    even if the diurnal trough goes quiet, and clean records land after
    it (mid-file corruption, not a truncatable torn tail).
    """

    def __init__(self, cluster, plan: List[SimEvent], *, metrics=None,
                 writer=None, asker=None):
        self.cluster = cluster
        self.plan = sorted(plan, key=lambda e: e.at_s)
        self.metrics = metrics
        self.writer = writer
        self.asker = asker
        self.outcomes: List[Dict] = []   # guarded-by: _lock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- control

    def start(self, t0: float) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(t0,), name="sim-ops", daemon=True
        )
        self._thread.start()

    def join(self, timeout: float) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("operations scheduler did not finish")

    def executed_kinds(self) -> Dict[str, int]:
        with self._lock:
            kinds: Dict[str, int] = {}
            for o in self.outcomes:
                if o["ok"]:
                    kinds[o["kind"]] = kinds.get(o["kind"], 0) + 1
            return kinds

    def failures(self) -> List[Dict]:
        with self._lock:
            return [o for o in self.outcomes if not o["ok"]]

    def event_windows(self) -> List[tuple]:
        """(start_s, end_s) wall intervals (offsets from workload start)
        each event actually occupied — the continuous SLO engine
        classifies burn-rate alerts against these: an alert inside a
        fault phase is the system working, one outside is a false
        alarm."""
        with self._lock:
            return [(o["t0_s"], o["t1_s"]) for o in self.outcomes
                    if "t0_s" in o and "t1_s" in o]

    # ------------------------------------------------------------ internals

    def _run(self, t0: float) -> None:
        for event in self.plan:
            delay = t0 + event.at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            outcome = {"kind": event.kind, "at_s": round(event.at_s, 3),
                       "ok": False, "detail": "",
                       "t0_s": round(time.monotonic() - t0, 3)}
            try:
                handler = {
                    CHAOS_CAMPAIGN: self._chaos_campaign,
                    ROLLING_RESTART: self._rolling_restart,
                    QUARANTINE: self._quarantine,
                    MEMBERSHIP_ADD: self._membership_add,
                    MEMBERSHIP_REMOVE: self._membership_remove,
                }[event.kind]
                outcome["detail"] = handler(event)
                outcome["ok"] = True
                if self.metrics is not None:
                    self.metrics.inc(metric.SIM_EVENTS_INJECTED)
            except Exception as e:  # recorded; the harness fails the run
                log.exception("sim event %s failed", event.kind)
                outcome["detail"] = f"{type(e).__name__}: {e}"
            outcome["t1_s"] = round(time.monotonic() - t0, 3)
            with self._lock:
                self.outcomes.append(outcome)

    def _leader(self) -> int:
        nid = self.cluster.wait_leader(timeout=15.0)
        if nid is None:
            raise RuntimeError("no leader to operate on")
        return nid

    def _post_leader(self, path: str, body: Dict, *,
                     attempts: int = 4,
                     avoid: Optional[int] = None) -> Dict:
        """POST an admin op that must land on the live leader.

        `wait_leader` and the POST are not atomic: the resolved node can
        step down in between (its /healthz hint may even still name
        itself), which is retryable operator business — re-resolve and
        re-post, like a human operator would. `avoid` drains leadership
        off that node first (decommission: never ask a node to remove
        itself)."""
        last: Optional[Exception] = None
        for attempt in range(attempts):
            leader = self._leader()
            try:
                if leader == avoid:
                    self.cluster.admin_post(leader, "/admin/transfer", {})
                    continue
                return self.cluster.admin_post(leader, path, body)
            except RuntimeError as e:
                last = e
                log.info("%s attempt %d on node %d failed: %s",
                         path, attempt, leader, e)
                time.sleep(0.5)
        raise RuntimeError(
            f"admin POST {path} kept failing across leaders: {last}"
        ) from last

    # -------------------------------------------------------------- events

    def _chaos_campaign(self, event: SimEvent) -> str:
        """Network chaos on every node's egress, with the leader's
        campaign ending in a tutoring blackout (degraded answers).

        The leader gets ONE campaign with both phases: CampaignRunner
        replaces (cancels) any running campaign on the same node, so
        posting the blackout separately would cancel the leader's chaos
        phase milliseconds in."""
        p = event.params
        leader = self._leader()
        t0 = None  # the leader's campaign clock starts at ITS post
        for nid in self.cluster.node_ids():
            phases = [{
                # "*" shapes BOTH the Raft egress and the tutoring
                # forward (FaultInjector.spec_for wildcard fallback).
                "target": "*",
                "duration_s": p["hold_s"], "drop": p["drop"],
                "delay_s": p["delay_s"],
                "delay_jitter_s": p["delay_jitter_s"],
                "duplicate": p["duplicate"],
            }]
            name = "sim-network-chaos"
            if nid == leader:
                phases.append({"target": "tutoring",
                               "duration_s": p["outage_hold_s"],
                               "drop": 1.0})
                name = "sim-chaos-then-blackout"
            self.cluster.admin_post(nid, "/admin/faults",
                                    {"campaign": {"name": name,
                                                  "phases": phases}})
            if nid == leader:
                # Anchor the probe window on the leader's POST, not on
                # some earlier instant: leader resolution and the other
                # nodes' POSTs can eat most of a second on a loaded
                # machine, and the blackout phase we probe runs on the
                # leader's clock.
                t0 = time.monotonic()
        # The campaign is introspectable while live: GET /admin/faults
        # (the plane used to be write-only).
        some = self.cluster.node_ids()[0]
        state = self.cluster.admin_get(some, "/admin/faults")
        if not state["campaign"]["active"]:
            raise RuntimeError(f"campaign not visible via GET: {state}")
        # Wait out the chaos phase, then probe while the leader's
        # blackout phase runs, guaranteeing the degraded path fires.
        end = t0 + p["hold_s"] + p["outage_hold_s"]
        time.sleep(max(0.0, t0 + p["hold_s"] + 0.1 - time.monotonic()))
        degraded = 0
        if self.asker is not None:
            while time.monotonic() < end - 0.2 and degraded < 3:
                if not self.asker():
                    time.sleep(0.1)
                    continue
                degraded += 1
        time.sleep(max(0.0, end - time.monotonic()))
        return (f"chaos {p['hold_s']}s on all nodes; tutoring blackout "
                f"{p['outage_hold_s']}s on leader {leader} "
                f"({degraded} degraded probes)")

    def _rolling_restart(self, event: SimEvent) -> str:
        """Planned maintenance: TimeoutNow handoff off the leader, then
        restart the ex-leader and wait for it to serve again. A transfer
        can abort under load (the chosen target lags or a send drops);
        that is retryable operator business, not a scenario failure."""
        resp = None
        for attempt in range(4):
            leader = self._leader()
            try:
                resp = self.cluster.admin_post(leader, "/admin/transfer",
                                               {})
                break
            except RuntimeError as e:
                log.info("transfer attempt %d failed: %s", attempt, e)
                time.sleep(0.5)
        if resp is None:
            raise RuntimeError("leadership transfer kept aborting")
        target = resp["target"]
        new_leader = self.cluster.wait_leader(timeout=15.0, exclude=leader)
        self.cluster.restart_node(leader)
        self.cluster.wait_healthy(leader, timeout=20.0)
        return (f"transferred {leader} -> {target} (observed leader "
                f"{new_leader}); restarted {leader}")

    def _quarantine(self, event: SimEvent) -> str:
        """Storage-recovery quarantine via the disk-fault admin plane:
        flip bits on a follower's disk writes, restart it — it must boot
        `storage_recovering`, rejoin via leader replication /
        InstallSnapshot, and heal.

        The restart follows the burst IMMEDIATELY: the victim's own
        snapshot compaction rewrites a clean snapshot and truncates the
        corrupt WAL prefix, so any post-clear dawdling can erase the
        evidence and boot the node clean. That compaction race is real
        (it depends on where the snapshot_every boundary lands), so a
        clean boot retries the whole burst rather than failing the run.
        """
        p = event.params
        attempts = 0
        while True:
            attempts += 1
            leader = self._leader()
            victim = next(n for n in self.cluster.node_ids()
                          if n != leader)
            self.cluster.admin_post(victim, "/admin/faults",
                                    {"target": "disk", "bit_flip": 1.0})
            # Acked writes DURING the burst: their WAL records on the
            # victim are corrupt on disk while a healthy quorum holds
            # them — the zero-loss SLO covers exactly these.
            for _ in range(5):
                if self.writer is not None:
                    self.writer()
                time.sleep(p["burst_s"] / 5)
            self.cluster.admin_post(victim, "/admin/faults",
                                    {"clear": "disk"})
            self.cluster.restart_node(victim)
            health = self.cluster.wait_healthy(victim, timeout=20.0)
            if health.get("storage_recovering"):
                break
            if attempts >= 3:
                raise RuntimeError(
                    f"node {victim} restarted clean {attempts} times — "
                    f"the disk-fault bursts never corrupted its WAL "
                    f"(healthz: {health})"
                )
            time.sleep(p["settle_s"])
        self.cluster.wait_until(
            victim, lambda h: not h.get("storage_recovering"),
            timeout=25.0, what="storage recovery to heal",
        )
        return (f"quarantined follower {victim} (attempt {attempts}); "
                "healed via rejoin")

    def _membership_add(self, event: SimEvent) -> str:
        nid, address = self.cluster.spawn_extra_node()
        resp = self._post_leader(
            "/admin/membership",
            {"op": "add", "id": nid, "address": address},
        )
        leader = self._leader()
        self.cluster.wait_until(
            leader, lambda h: str(nid) in h.get("members", {}),
            timeout=15.0, what=f"member {nid} visible on leader",
        )
        return f"added node {nid} at {address} (index {resp['index']})"

    def _membership_remove(self, event: SimEvent) -> str:
        nid = self.cluster.extra_node_id()
        if nid is None:
            raise RuntimeError("no membership-added node to remove")
        self._post_leader("/admin/membership",
                          {"op": "remove", "id": nid}, avoid=nid)
        leader = self._leader()
        self.cluster.wait_until(
            leader, lambda h: str(nid) not in h.get("members", {}),
            timeout=15.0, what=f"member {nid} gone from leader view",
        )
        self.cluster.stop_node(nid)
        return f"removed node {nid} and stopped it"
