"""The operations schedule: planned and unplanned events injected mid-run.

`plan_events` is — like the workload trace — a pure function of the
config: the schedule (what happens, when, with which parameters) comes
from the seed, so a failed run replays. `OperationsScheduler` executes
the plan against a live `SimCluster` strictly through the surfaces real
operators use: `POST /admin/faults` (including timed campaigns),
`POST /admin/transfer` (TimeoutNow leadership handoff), the disk-fault
admin plane for the storage-recovery quarantine, and
`POST /admin/membership` for the add/remove — then verifies each event's
observable outcome from `/healthz` (`GET /admin/faults` for campaigns).

Each event records an outcome dict; any `ok=False` outcome fails the
run's verdict (the harness feeds `failures()` into `evaluate_slos` as
the `events_completed` check), so the acceptance criteria —
>=1 transfer, >=1 quarantine+rejoin, >=1 membership change — are proven,
not assumed.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
import urllib.parse
from typing import Dict, List, Optional

from ..config import SimConfig
from ..utils import metrics_registry as metric

log = logging.getLogger(__name__)

CHAOS_CAMPAIGN = "chaos_campaign"
ROLLING_RESTART = "rolling_restart"
QUARANTINE = "quarantine"
MEMBERSHIP_ADD = "membership_add"
MEMBERSHIP_REMOVE = "membership_remove"
# Tutoring-fleet drills ([sim] tutoring_nodes > 1): brownout-then-
# blackout of ONE fleet member (hedge wins, then router spill), a
# drain-and-rejoin cycle (ejection, warm-up re-admission, affinity
# restored), and an autoscale add/drain/remove under load.
TUTORING_BLACKOUT = "tutoring_blackout"
TUTORING_DRAIN = "tutoring_drain_rejoin"
TUTORING_AUTOSCALE = "tutoring_autoscale"
# Resumable-stream drill: inject a mid-stream loss (the chaos `error`
# fault fires AFTER the first delivered chunk) on the streamed probe
# session's affinity node — the router must resume the answer at the
# delivered token offset on the spill node, and the client's digest
# check proves no token was duplicated or dropped across the failover.
TUTORING_STREAM_KILL = "tutoring_stream_kill"
# Bulk grading night ([sim] bulk_scoring): an instructor-scale score job
# fans every submitted assignment to the tutoring fleet's background
# scoring tenant via the LMS admin plane, mid-run, while student traffic
# keeps flowing. The job must COMPLETE; interactive p95 must not move.
BULK_GRADING = "bulk_grading_night"
# Sharded-control-plane drills ([sim] lms_groups > 1): sever ONE Raft
# group's quorum links on its leader via the per-group fault target
# `raft:<gid>` (the other groups must keep serving and the group must
# re-elect), and a live group split — POST /admin/reshard moves a course
# between groups mid-diurnal-peak, under a network-chaos overlay, with
# the routing-map flip verified on every node.
GROUP_LEADER_LOSS = "group_leader_loss"
GROUP_SPLIT = "group_split"

# Events that are OPERATIONS, not faults: the continuous SLO engine
# classifies burn alerts against fault windows only, so a latency alert
# raised while (e.g.) the bulk-grading job runs is a FALSE ALARM and
# fails the verdict — exactly the "interactive p95 unchanged while the
# job runs" claim, enforced by the existing alarm discipline.
NON_FAULT_KINDS = frozenset({BULK_GRADING})

# The ops bot's fixed ask: the fleet drills resolve ITS affinity node
# via GET /admin/tutoring/route and then fault/drain exactly that node,
# so a probe's hedge/spill is guaranteed to exercise the router (the
# harness's asker issues this same query).
PROBE_QUERY = "ops bot probe: what is Raft?"
# The streamed probe's session id: every streamer call converses in this
# one session, so the stream-kill drill can resolve (and fault) the node
# holding its transcript via /admin/tutoring/route?session=.
STREAM_SESSION_ID = "ops-bot-stream-drill"


@dataclasses.dataclass(frozen=True)
class SimEvent:
    at_s: float      # offset from workload start
    kind: str
    params: Dict[str, float]

    def key(self) -> str:
        items = ",".join(f"{k}={v}" for k, v in sorted(self.params.items()))
        return f"{self.at_s:.6f}|{self.kind}|{items}"


def _jitter(rng: random.Random, frac: float, width: float) -> float:
    return frac + rng.uniform(-width, width)


def plan_events(cfg: SimConfig) -> List[SimEvent]:
    """The semester's operations calendar, scaled to `duration_s`.

    Layout (fractions of the run, seed-jittered): an early network-chaos
    campaign whose last phase blacks out the tutoring hop (degraded
    answers, breaker open/close), a rolling restart of the leader via
    TimeoutNow transfer, a follower quarantined into storage recovery via
    disk bit flips, then a membership add and the matching remove.
    """
    if not cfg.events:
        return []
    rng = random.Random(cfg.seed ^ 0x5EED)
    T = cfg.duration_s
    chaos_hold = max(1.0, 0.10 * T)
    # The blackout must outlast the continuous SLO engine's fast window
    # (max(1.0, 0.06*T)) with margin: the fast-window burn alert needs a
    # span where the window sits fully inside the outage, plus the
    # sustain requirement — a blackout shorter than the window can only
    # ever produce diluted ratios.
    outage_hold = max(1.5, 0.08 * T)
    events = [
        SimEvent(
            at_s=_jitter(rng, 0.12, 0.02) * T, kind=CHAOS_CAMPAIGN,
            params={
                "drop": 0.10, "delay_s": 0.002, "delay_jitter_s": 0.01,
                "duplicate": 0.05, "hold_s": round(chaos_hold, 3),
                "outage_hold_s": round(outage_hold, 3),
            },
        ),
        SimEvent(at_s=_jitter(rng, 0.38, 0.02) * T, kind=ROLLING_RESTART,
                 params={}),
        SimEvent(
            at_s=_jitter(rng, 0.55, 0.02) * T, kind=QUARANTINE,
            params={"burst_s": round(max(0.8, 0.05 * T), 3),
                    "settle_s": round(max(0.6, 0.03 * T), 3)},
        ),
        SimEvent(at_s=_jitter(rng, 0.75, 0.02) * T, kind=MEMBERSHIP_ADD,
                 params={}),
        SimEvent(at_s=_jitter(rng, 0.90, 0.02) * T, kind=MEMBERSHIP_REMOVE,
                 params={}),
    ]
    if cfg.bulk_scoring:
        # The "night" lands in the post-chaos lull before the rolling
        # restart: the job must share the chip with live student traffic
        # (that is the claim), but a restart mid-poll would reset the
        # counters the completion check reads.
        events.append(SimEvent(
            at_s=_jitter(rng, 0.26, 0.02) * T, kind=BULK_GRADING,
            params={"timeout_s": round(max(6.0, 0.4 * T), 3)},
        ))
    if cfg.lms_groups > 1:
        # Group drills straddle the diurnal PEAK (0.5T): the leader loss
        # lands just before it, the live split right on it — the handoff
        # has to freeze/stream/flip while traffic is at its densest and a
        # chaos overlay shapes the wires.
        events += [
            SimEvent(
                at_s=_jitter(rng, 0.45, 0.02) * T, kind=GROUP_LEADER_LOSS,
                params={"gid": 1,
                        "hold_s": round(max(1.2, 0.05 * T), 3)},
            ),
            SimEvent(
                at_s=_jitter(rng, 0.52, 0.02) * T, kind=GROUP_SPLIT,
                params={"course": 0,
                        "chaos_s": round(max(2.0, 0.10 * T), 3)},
            ),
        ]
    if cfg.tutoring_nodes > 1:
        # Fleet drills land AFTER the rolling restart (0.38T): the node
        # that routes (and counts hedges/spills) must not be restarted
        # out from under the drill's counter deltas.
        events += [
            SimEvent(
                at_s=_jitter(rng, 0.48, 0.02) * T, kind=TUTORING_BLACKOUT,
                params={
                    "brownout_s": round(max(2.0, 0.10 * T), 3),
                    "outage_s": round(max(1.5, 0.08 * T), 3),
                    "delay_s": 0.6,
                },
            ),
            SimEvent(
                at_s=_jitter(rng, 0.58, 0.02) * T,
                kind=TUTORING_STREAM_KILL,
                params={"error_s": round(max(1.2, 0.06 * T), 3)},
            ),
            SimEvent(at_s=_jitter(rng, 0.68, 0.02) * T,
                     kind=TUTORING_DRAIN, params={}),
            SimEvent(at_s=_jitter(rng, 0.84, 0.02) * T,
                     kind=TUTORING_AUTOSCALE,
                     params={"hold_s": round(max(0.8, 0.04 * T), 3)}),
        ]
    return events


class OperationsScheduler:
    """Executes a plan against a `SimCluster` on its own thread.

    `writer` is a callable issuing one guaranteed acked write (the
    harness's ops-bot client): the quarantine event uses it to make sure
    corrupted-on-disk records actually exist during the bit-flip burst
    even if the diurnal trough goes quiet, and clean records land after
    it (mid-file corruption, not a truncatable torn tail).
    """

    def __init__(self, cluster, plan: List[SimEvent], *, metrics=None,
                 writer=None, asker=None, streamer=None, ledger=None):
        self.cluster = cluster
        self.plan = sorted(plan, key=lambda e: e.at_s)
        self.metrics = metrics
        self.writer = writer
        self.asker = asker
        # One STREAMED probe over a fixed session id (STREAM_SESSION_ID);
        # returns True when the stream completed digest-intact. The
        # stream-kill drill drives it under an injected mid-stream loss.
        self.streamer = streamer
        self.ledger = ledger
        self.outcomes: List[Dict] = []   # guarded-by: _lock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- control

    def start(self, t0: float) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(t0,), name="sim-ops", daemon=True
        )
        self._thread.start()

    def join(self, timeout: float) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("operations scheduler did not finish")

    def executed_kinds(self) -> Dict[str, int]:
        with self._lock:
            kinds: Dict[str, int] = {}
            for o in self.outcomes:
                if o["ok"]:
                    kinds[o["kind"]] = kinds.get(o["kind"], 0) + 1
            return kinds

    def failures(self) -> List[Dict]:
        with self._lock:
            return [o for o in self.outcomes if not o["ok"]]

    def event_windows(self) -> List[tuple]:
        """(start_s, end_s) wall intervals (offsets from workload start)
        each FAULT event actually occupied — the continuous SLO engine
        classifies burn-rate alerts against these: an alert inside a
        fault phase is the system working, one outside is a false alarm.
        Non-fault operations (NON_FAULT_KINDS — the bulk-grading night)
        are excluded on purpose: background scoring promises NOT to move
        interactive latency, so an alert during it must fail the run,
        not be excused by it."""
        with self._lock:
            return [(o["t0_s"], o["t1_s"]) for o in self.outcomes
                    if "t0_s" in o and "t1_s" in o
                    and o["kind"] not in NON_FAULT_KINDS]

    # ------------------------------------------------------------ internals

    def _run(self, t0: float) -> None:
        for event in self.plan:
            delay = t0 + event.at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            outcome = {"kind": event.kind, "at_s": round(event.at_s, 3),
                       "ok": False, "detail": "",
                       "t0_s": round(time.monotonic() - t0, 3)}
            try:
                handler = {
                    CHAOS_CAMPAIGN: self._chaos_campaign,
                    ROLLING_RESTART: self._rolling_restart,
                    QUARANTINE: self._quarantine,
                    MEMBERSHIP_ADD: self._membership_add,
                    MEMBERSHIP_REMOVE: self._membership_remove,
                    TUTORING_BLACKOUT: self._tutoring_blackout,
                    TUTORING_DRAIN: self._tutoring_drain,
                    TUTORING_AUTOSCALE: self._tutoring_autoscale,
                    TUTORING_STREAM_KILL: self._tutoring_stream_kill,
                    BULK_GRADING: self._bulk_grading,
                    GROUP_LEADER_LOSS: self._group_leader_loss,
                    GROUP_SPLIT: self._group_split,
                }[event.kind]
                outcome["detail"] = handler(event)
                outcome["ok"] = True
                if self.metrics is not None:
                    self.metrics.inc(metric.SIM_EVENTS_INJECTED)
            except Exception as e:  # recorded; the harness fails the run
                log.exception("sim event %s failed", event.kind)
                outcome["detail"] = f"{type(e).__name__}: {e}"
            outcome["t1_s"] = round(time.monotonic() - t0, 3)
            with self._lock:
                self.outcomes.append(outcome)

    def _leader(self) -> int:
        nid = self.cluster.wait_leader(timeout=15.0)
        if nid is None:
            raise RuntimeError("no leader to operate on")
        return nid

    def _post_leader(self, path: str, body: Dict, *,
                     attempts: int = 4,
                     avoid: Optional[int] = None) -> Dict:
        """POST an admin op that must land on the live leader.

        `wait_leader` and the POST are not atomic: the resolved node can
        step down in between (its /healthz hint may even still name
        itself), which is retryable operator business — re-resolve and
        re-post, like a human operator would. `avoid` drains leadership
        off that node first (decommission: never ask a node to remove
        itself)."""
        last: Optional[Exception] = None
        for attempt in range(attempts):
            leader = self._leader()
            try:
                if leader == avoid:
                    self.cluster.admin_post(leader, "/admin/transfer", {})
                    continue
                return self.cluster.admin_post(leader, path, body)
            except RuntimeError as e:
                last = e
                log.info("%s attempt %d on node %d failed: %s",
                         path, attempt, leader, e)
                time.sleep(0.5)
        raise RuntimeError(
            f"admin POST {path} kept failing across leaders: {last}"
        ) from last

    # -------------------------------------------------------------- events

    def _chaos_campaign(self, event: SimEvent) -> str:
        """Network chaos on every node's egress, with the leader's
        campaign ending in a tutoring blackout (degraded answers).

        The leader gets ONE campaign with both phases: CampaignRunner
        replaces (cancels) any running campaign on the same node, so
        posting the blackout separately would cancel the leader's chaos
        phase milliseconds in."""
        p = event.params
        leader = self._leader()
        t0 = None  # the leader's campaign clock starts at ITS post
        for nid in self.cluster.node_ids():
            phases = [{
                # "*" shapes BOTH the Raft egress and the tutoring
                # forward (FaultInjector.spec_for wildcard fallback).
                "target": "*",
                "duration_s": p["hold_s"], "drop": p["drop"],
                "delay_s": p["delay_s"],
                "delay_jitter_s": p["delay_jitter_s"],
                "duplicate": p["duplicate"],
            }]
            name = "sim-network-chaos"
            if nid == leader:
                phases.append({"target": "tutoring",
                               "duration_s": p["outage_hold_s"],
                               "drop": 1.0})
                name = "sim-chaos-then-blackout"
            self.cluster.admin_post(nid, "/admin/faults",
                                    {"campaign": {"name": name,
                                                  "phases": phases}})
            if nid == leader:
                # Anchor the probe window on the leader's POST, not on
                # some earlier instant: leader resolution and the other
                # nodes' POSTs can eat most of a second on a loaded
                # machine, and the blackout phase we probe runs on the
                # leader's clock.
                t0 = time.monotonic()
        # The campaign is introspectable while live: GET /admin/faults
        # (the plane used to be write-only).
        some = self.cluster.node_ids()[0]
        state = self.cluster.admin_get(some, "/admin/faults")
        if not state["campaign"]["active"]:
            raise RuntimeError(f"campaign not visible via GET: {state}")
        # Wait out the chaos phase, then probe while the leader's
        # blackout phase runs, guaranteeing the degraded path fires.
        end = t0 + p["hold_s"] + p["outage_hold_s"]
        time.sleep(max(0.0, t0 + p["hold_s"] + 0.1 - time.monotonic()))
        degraded = 0
        if self.asker is not None:
            while time.monotonic() < end - 0.2 and degraded < 3:
                if not self.asker():
                    time.sleep(0.1)
                    continue
                degraded += 1
        time.sleep(max(0.0, end - time.monotonic()))
        return (f"chaos {p['hold_s']}s on all nodes; tutoring blackout "
                f"{p['outage_hold_s']}s on leader {leader} "
                f"({degraded} degraded probes)")

    def _rolling_restart(self, event: SimEvent) -> str:
        """Planned maintenance: TimeoutNow handoff off the leader, then
        restart the ex-leader and wait for it to serve again. A transfer
        can abort under load (the chosen target lags or a send drops);
        that is retryable operator business, not a scenario failure."""
        resp = None
        for attempt in range(4):
            leader = self._leader()
            try:
                resp = self.cluster.admin_post(leader, "/admin/transfer",
                                               {})
                break
            except RuntimeError as e:
                log.info("transfer attempt %d failed: %s", attempt, e)
                time.sleep(0.5)
        if resp is None:
            raise RuntimeError("leadership transfer kept aborting")
        target = resp["target"]
        new_leader = self.cluster.wait_leader(timeout=15.0, exclude=leader)
        self.cluster.restart_node(leader)
        self.cluster.wait_healthy(leader, timeout=20.0)
        return (f"transferred {leader} -> {target} (observed leader "
                f"{new_leader}); restarted {leader}")

    def _quarantine(self, event: SimEvent) -> str:
        """Storage-recovery quarantine via the disk-fault admin plane:
        flip bits on a follower's disk writes, restart it — it must boot
        `storage_recovering`, rejoin via leader replication /
        InstallSnapshot, and heal.

        The restart follows the burst IMMEDIATELY: the victim's own
        snapshot compaction rewrites a clean snapshot and truncates the
        corrupt WAL prefix, so any post-clear dawdling can erase the
        evidence and boot the node clean. That compaction race is real
        (it depends on where the snapshot_every boundary lands), so a
        clean boot retries the whole burst rather than failing the run.
        """
        p = event.params
        attempts = 0
        while True:
            attempts += 1
            leader = self._leader()
            victim = next(n for n in self.cluster.node_ids()
                          if n != leader)
            self.cluster.admin_post(victim, "/admin/faults",
                                    {"target": "disk", "bit_flip": 1.0})
            # Acked writes DURING the burst: their WAL records on the
            # victim are corrupt on disk while a healthy quorum holds
            # them — the zero-loss SLO covers exactly these.
            for _ in range(5):
                if self.writer is not None:
                    self.writer()
                time.sleep(p["burst_s"] / 5)
            self.cluster.admin_post(victim, "/admin/faults",
                                    {"clear": "disk"})
            self.cluster.restart_node(victim)
            health = self.cluster.wait_healthy(victim, timeout=20.0)
            if health.get("storage_recovering"):
                break
            if attempts >= 3:
                raise RuntimeError(
                    f"node {victim} restarted clean {attempts} times — "
                    f"the disk-fault bursts never corrupted its WAL "
                    f"(healthz: {health})"
                )
            time.sleep(p["settle_s"])
        self.cluster.wait_until(
            victim, lambda h: not h.get("storage_recovering"),
            timeout=25.0, what="storage recovery to heal",
        )
        return (f"quarantined follower {victim} (attempt {attempts}); "
                "healed via rejoin")

    def _membership_add(self, event: SimEvent) -> str:
        nid, address = self.cluster.spawn_extra_node()
        resp = self._post_leader(
            "/admin/membership",
            {"op": "add", "id": nid, "address": address},
        )
        leader = self._leader()
        self.cluster.wait_until(
            leader, lambda h: str(nid) in h.get("members", {}),
            timeout=15.0, what=f"member {nid} visible on leader",
        )
        return f"added node {nid} at {address} (index {resp['index']})"

    def _membership_remove(self, event: SimEvent) -> str:
        nid = self.cluster.extra_node_id()
        if nid is None:
            raise RuntimeError("no membership-added node to remove")
        self._post_leader("/admin/membership",
                          {"op": "remove", "id": nid}, avoid=nid)
        leader = self._leader()
        self.cluster.wait_until(
            leader, lambda h: str(nid) not in h.get("members", {}),
            timeout=15.0, what=f"member {nid} gone from leader view",
        )
        self.cluster.stop_node(nid)
        return f"removed node {nid} and stopped it"

    def _bulk_grading(self, event: SimEvent) -> str:
        """Bulk grading night: fan every submitted assignment to the
        tutoring fleet's background scoring tenant via the LMS leader's
        admin plane (POST /admin/score routes off the hot affinity nodes
        — lms/tutoring_pool.plan_background), then poll the placed
        node's GET /admin/score/<id> until the job completes. Student
        traffic keeps flowing the whole time; the continuous SLO engine
        treats this window as NON-fault, so a scoring-induced latency
        alert fails the run — "interactive p95 unchanged while the job
        runs" is enforced, not assumed."""
        import json as _json
        import urllib.request

        p = event.params
        resp = self._post_leader("/admin/score", {"purpose": "grading"})
        job_id = resp["job_id"]
        health = resp["health"]
        submitted = int(resp.get("submitted_texts", 0))
        deadline = time.monotonic() + p["timeout_s"]
        doc: Dict = {}
        while time.monotonic() < deadline:
            # Poll the tutoring node directly (leadership may move while
            # the job runs; the placing node's admin plane is sticky).
            req = urllib.request.Request(
                f"http://{health}/admin/score/{job_id}", method="GET"
            )
            try:
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    doc = _json.loads(r.read().decode())
            except Exception as e:  # transient poll failure: keep trying
                log.info("bulk-grading poll failed: %s", e)
                time.sleep(0.2)
                continue
            if doc.get("status") in ("done", "failed"):
                break
            time.sleep(0.1)
        if doc.get("status") != "done":
            raise RuntimeError(
                f"bulk grading job {job_id} did not complete in "
                f"{p['timeout_s']}s: {doc.get('status')!r} "
                f"({doc.get('error')})"
            )
        results = doc.get("results") or []
        if submitted and len(results) != submitted:
            raise RuntimeError(
                f"bulk grading job {job_id} returned {len(results)} "
                f"results for {submitted} submissions"
            )
        return (f"graded {len(results)} submissions in {doc.get('quanta')}"
                f" preemptible quanta on {resp.get('node')} "
                f"({doc.get('scored_tokens')} tokens scored in the idle "
                "lanes, interactive traffic untouched)")

    # ------------------------------------------------------ group drills

    def _group_is_leader(self, nid: int, gid: int) -> bool:
        doc = self.cluster.group_topology(nid)
        row = doc.get("groups", {}).get(str(gid), {})
        return bool(row.get("is_leader"))

    def _group_leader_loss(self, event: SimEvent) -> str:
        """Sever ONE Raft group's quorum links on its leader via the
        per-group fault target `raft:<gid>` (a timed campaign, the same
        plane operators use). The group must re-elect on another node
        while every OTHER group — including the meta group — keeps its
        leader untouched."""
        p = event.params
        gid = int(p["gid"])
        victim = self.cluster.wait_group_leader(gid, timeout=15.0)
        if victim is None:
            raise RuntimeError(f"group {gid} has no leader to kill")
        self.cluster.admin_post(victim, "/admin/faults", {"campaign": {
            "name": f"sim-group{gid}-leader-loss",
            "phases": [{"target": f"raft:{gid}",
                        "duration_s": p["hold_s"], "drop": 1.0}],
        }})
        t0 = time.monotonic()
        new_leader = None
        deadline = t0 + p["hold_s"] + 10.0
        while time.monotonic() < deadline:
            for nid in self.cluster.node_ids():
                if nid == victim:
                    continue
                try:
                    if self._group_is_leader(nid, gid):
                        new_leader = nid
                        break
                except Exception:
                    continue
            if new_leader is not None:
                break
            time.sleep(0.05)
        if new_leader is None:
            raise RuntimeError(
                f"group {gid} elected no replacement leader after its "
                f"leader {victim} lost its group links"
            )
        # Wait out the campaign so event_windows covers the whole fault.
        time.sleep(max(0.0, t0 + p["hold_s"] - time.monotonic()))
        return (f"severed raft:{gid} on leader {victim} for "
                f"{p['hold_s']}s; group re-elected node {new_leader}")

    def _group_split(self, event: SimEvent) -> str:
        """Live group split mid-diurnal-peak: move one course's key
        range to the neighbor group through POST /admin/reshard — the
        staged freeze/stream/flip handoff — while a network-chaos
        overlay shapes every node's egress. The routing-map flip must
        become visible on EVERY node's router."""
        p = event.params
        doc = self.cluster.routing_map_doc()
        course = f"course{int(p['course'])}"
        courses = doc.get("courses", {})
        if course not in courses:
            raise RuntimeError(
                f"course {course!r} missing from routing map {doc}"
            )
        src = int(courses[course])
        n_groups = int(doc.get("n_groups", 1))
        dst = (src + 1) % n_groups
        v0 = int(doc.get("version", 1))
        for nid in self.cluster.node_ids():
            self.cluster.admin_post(nid, "/admin/faults", {"campaign": {
                "name": "sim-split-chaos",
                "phases": [{"target": "*", "duration_s": p["chaos_s"],
                            "drop": 0.05, "delay_s": 0.002}],
            }})
        resp = self.cluster.reshard(course, dst)
        self._wait(
            lambda: all(
                int(self.cluster.routing_map_doc(nid).get("version", 0))
                > v0
                for nid in self.cluster.node_ids()
            ),
            15.0, "routing-map flip visible on every node",
        )
        if self.ledger is not None:
            self.ledger.note_reshard(course, src, dst,
                                     int(resp.get("version", v0 + 1)))
        return (f"moved {course} group {src} -> {dst} under chaos "
                f"(map v{v0} -> v{resp.get('version')}, "
                f"{resp.get('moved_users')} users)")

    # ------------------------------------------------------ fleet drills

    def _probe_route(self, nid: int, session_id: str = "") -> Dict:
        """Where the ring on LMS node `nid` would send the ops bot's
        probe query (GET /admin/tutoring/route) — or, with `session_id`,
        the probe SESSION's sticky key (the node holding its transcript
        and pinned prefix blocks)."""
        path = ("/admin/tutoring/route?session="
                + urllib.parse.quote(session_id) if session_id else
                "/admin/tutoring/route?q="
                + urllib.parse.quote(PROBE_QUERY))
        doc = self.cluster.admin_get(nid, path)
        if not doc.get("order"):
            raise RuntimeError(f"empty tutoring route on node {nid}: "
                               f"{doc}")
        return doc

    def _fleet_counter(self, name: str) -> int:
        """Summed across every live LMS node: whichever node leads (and
        therefore routes) during the drill contributes its counters."""
        total = 0
        for nid in self.cluster.node_ids():
            try:
                snap = self.cluster.metrics_snapshot(nid)
            except Exception:
                continue
            total += int(snap.get("counters", {}).get(name, 0))
        return total

    def _probe_until(self, counter: str, baseline: int, end: float,
                     settle_s: float = 0.05) -> int:
        """Drive ops-bot asks until `counter` moves past `baseline` or
        the window closes; returns the final reading."""
        value = baseline
        while time.monotonic() < end - 0.1:
            if self.asker is not None:
                self.asker()
            value = self._fleet_counter(counter)
            if value > baseline:
                break
            time.sleep(settle_s)
        return value

    def _tutoring_blackout(self, event: SimEvent) -> str:
        """Kill-one-of-N: brownout (injected delay) then full blackout
        of exactly the probe query's affinity node, via the per-node
        fault target `tutoring:<i>`. The brownout must produce a hedge
        win (the second choice answers while the affinity node sits on
        the request); the blackout must produce a router spill within
        its own window — tail-tolerance proven from /metrics, not
        assumed."""
        p = event.params
        leader = self._leader()
        route = self._probe_route(leader)
        idx = route["order"][0]["index"]
        self.cluster.admin_post(leader, "/admin/faults", {"campaign": {
            "name": "sim-fleet-brownout-blackout",
            "phases": [
                {"target": f"tutoring:{idx}",
                 "duration_s": p["brownout_s"], "delay_s": p["delay_s"]},
                {"target": f"tutoring:{idx}",
                 "duration_s": p["outage_s"], "drop": 1.0},
            ],
        }})
        t0 = time.monotonic()
        wins0 = self._fleet_counter(metric.TUTORING_HEDGE_WINS)
        wins = self._probe_until(metric.TUTORING_HEDGE_WINS, wins0,
                                 t0 + p["brownout_s"])
        time.sleep(max(0.0, t0 + p["brownout_s"] - time.monotonic()))
        # Baseline AFTER the brownout: hedge wins are served
        # off-affinity and count as spills too, so a pre-brownout
        # baseline would make the blackout-phase assertion vacuous.
        spills0 = self._fleet_counter(metric.TUTORING_SPILLS)
        spills = self._probe_until(
            metric.TUTORING_SPILLS, spills0,
            t0 + p["brownout_s"] + p["outage_s"],
        )
        time.sleep(max(0.0, t0 + p["brownout_s"] + p["outage_s"]
                       - time.monotonic()))
        if wins <= wins0:
            raise RuntimeError(
                f"no hedge win during the {p['brownout_s']}s brownout "
                f"of tutoring:{idx}"
            )
        if spills <= spills0:
            raise RuntimeError(
                f"no router spill during the {p['outage_s']}s blackout "
                f"of tutoring:{idx}"
            )
        return (f"browned out tutoring:{idx} {p['brownout_s']}s "
                f"(hedge wins +{wins - wins0}), blacked it out "
                f"{p['outage_s']}s (spills +{spills - spills0}); the "
                "router spilled within the outage window")

    def _tutoring_drain(self, event: SimEvent) -> str:
        """Elastic drain-and-rejoin, MID-SESSION: POST /admin/drain on
        the streamed probe session's affinity node (the one holding its
        transcript), watch the router eject it (health poller), prove
        the session's next streamed turn completes on the second choice
        (correctness never depends on node-local session warmth), end
        the drain, and verify the ring routes the session key BACK to
        the node once its warm-up ramp finishes — cache affinity
        restored, not just liveness."""
        leader = self._leader()
        if self.streamer is not None:
            self.streamer()  # seed the session so a transcript is live
        route = self._probe_route(leader, session_id=STREAM_SESSION_ID)
        idx = route["order"][0]["index"]
        address = route["order"][0]["address"]
        self.cluster.tutoring_admin_post(idx, "/admin/drain",
                                         {"drain": True})
        self._wait(lambda: self.cluster.tutoring_healthz(idx)
                   .get("draining") and
                   self.cluster.tutoring_healthz(idx).get("queued") == 0,
                   10.0, f"tutoring node {idx} drained")
        self._wait(lambda: self._fleet_state(leader, address)
                   in ("draining", "ejected"),
                   10.0, f"router ejected {address}")
        if self.asker is not None:
            self.asker()  # served by the second choice while drained
        mid = self._probe_route(self._leader(),
                                session_id=STREAM_SESSION_ID)
        if mid["order"] and mid["order"][0]["index"] == idx:
            raise RuntimeError(
                f"session still routed to draining node {idx}: {mid}"
            )
        if self.streamer is not None and not self.streamer():
            raise RuntimeError(
                "streamed session turn failed while its affinity node "
                f"{idx} drained (must be served off-node)"
            )
        self.cluster.tutoring_admin_post(idx, "/admin/drain",
                                         {"drain": False})
        self._wait(lambda: self._fleet_state(leader, address)
                   in ("warming", "ok"),
                   10.0, f"router re-admitted {address}")
        self._wait(lambda: self._fleet_state(leader, address) == "ok",
                   10.0, f"warm-up of {address} finished")
        back = self._probe_route(leader, session_id=STREAM_SESSION_ID)
        if back["order"][0]["index"] != idx:
            raise RuntimeError(
                f"affinity not restored after rejoin: session routes to "
                f"{back['order'][0]} instead of node {idx}"
            )
        return (f"drained tutoring:{idx} mid-session (router ejected "
                "it, the session's streamed turn completed off-node), "
                "rejoined with warm-up; session affinity restored to "
                "the same node")

    def _tutoring_stream_kill(self, event: SimEvent) -> str:
        """Kill-mid-stream: the chaos `error` fault on the session's
        affinity node makes every stream from it die right AFTER its
        first delivered chunk — too late to hedge (hedging is
        before-first-byte only), so the router must resume the answer
        at the delivered token offset on the spill node. Evidence is
        demanded from both ends: the fleet's stream_resumes counter
        moves, and the client completes a streamed answer whose
        assembled text matches the final chunk's digest (no token
        duplicated or dropped across the failover)."""
        p = event.params
        if self.streamer is None:
            raise RuntimeError("stream-kill drill needs a streamer probe")
        leader = self._leader()
        self.streamer()  # seed the session (affinity + transcript)
        route = self._probe_route(leader, session_id=STREAM_SESSION_ID)
        if len(route["order"]) < 2:
            raise RuntimeError(
                f"stream-kill drill needs a spill candidate: {route}"
            )
        idx = route["order"][0]["index"]
        resumes0 = self._fleet_counter(metric.STREAM_RESUMES)
        self.cluster.admin_post(leader, "/admin/faults", {"campaign": {
            "name": "sim-stream-kill",
            "phases": [{"target": f"tutoring:{idx}",
                        "duration_s": p["error_s"], "error": 1.0}],
        }})
        t0 = time.monotonic()
        end = t0 + p["error_s"]
        intact = 0
        resumes = resumes0
        while time.monotonic() < end - 0.1:
            if self.streamer():
                intact += 1
            resumes = self._fleet_counter(metric.STREAM_RESUMES)
            if resumes > resumes0 and intact >= 1:
                break
            time.sleep(0.05)
        time.sleep(max(0.0, end - time.monotonic()))
        if resumes <= resumes0:
            raise RuntimeError(
                f"no resume-at-offset failover during the "
                f"{p['error_s']}s mid-stream loss on tutoring:{idx}"
            )
        if intact < 1:
            raise RuntimeError(
                "no digest-intact streamed answer completed during the "
                f"mid-stream loss on tutoring:{idx}"
            )
        return (f"injected mid-stream loss on tutoring:{idx} for "
                f"{p['error_s']}s; +{resumes - resumes0} resume-at-"
                f"offset failovers, {intact} streamed answer(s) "
                "completed digest-intact")

    def _tutoring_autoscale(self, event: SimEvent) -> str:
        """Autoscaling drill: add a fleet member under load (every LMS
        router admits it, warm-up weighted), hold, then drain + remove
        it — the add/remove remaps only the new node's ~1/N key share
        (rendezvous), so the survivors' prefix caches stay warm."""
        p = event.params
        idx, address, health = self.cluster.spawn_tutoring_node()
        for nid in self.cluster.node_ids():
            self.cluster.admin_post(nid, "/admin/tutoring",
                                    {"op": "add", "address": address,
                                     "health": health})
        leader = self._leader()
        self._wait(lambda: self._fleet_state(leader, address)
                   in ("warming", "ok"),
                   10.0, f"router admitted {address}")
        time.sleep(p["hold_s"])  # serve under load as a fleet of N+1
        self.cluster.tutoring_admin_post(idx, "/admin/drain",
                                         {"drain": True})
        self._wait(lambda: self.cluster.tutoring_healthz(idx)
                   .get("queued") == 0,
                   10.0, f"autoscaled node {idx} drained")
        for nid in self.cluster.node_ids():
            self.cluster.admin_post(nid, "/admin/tutoring",
                                    {"op": "remove", "address": address})
        self.cluster.stop_tutoring_node(idx)
        return (f"scaled the fleet up with {address} under load, then "
                "drained and removed it")

    def _fleet_state(self, nid: int, address: str) -> Optional[str]:
        health = self.cluster.healthz(nid)
        for node in health.get("tutoring_fleet", {}).get("nodes", ()):
            if node["address"] == address:
                return node["state"]
        return None

    def _wait(self, pred, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if pred():
                    return
            except Exception:
                pass
            time.sleep(0.05)
        raise RuntimeError(f"timed out waiting for {what}")
