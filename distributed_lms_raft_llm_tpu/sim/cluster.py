"""The cluster under test: real gRPC nodes with the real admin plane.

Boots N LMS nodes (Raft + LMS + FileTransfer servicers, per-node fault
injectors, breaker, and the SAME admin/health plane `serving/lms_server`
serves — `make_admin`/`make_health` are imported, not re-implemented) plus
a tutoring node, all on one background asyncio loop, with thread-safe
control methods for the workload workers and the operations scheduler:
restart a node in place (same port, same data dir — the storage-recovery
path runs for real), spawn an extra node for a membership add, scrape
`/metrics`, and drive `POST`/`GET /admin/*` over actual HTTP.

Ports are allocated once and pinned for the cluster's lifetime so a
restarted node comes back at its advertised address (peers re-dial it,
clients re-discover it).

The default tutoring engine is `EchoEngine` — a wire-complete stand-in
that exercises the REAL BatchingQueue admission, deadline shedding, HMAC
path, and gRPC plumbing without paying an XLA compile; the tier-2 soak
swaps in the real tiny JAX engine (`[sim] tutoring_engine = "tiny"`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from ..config import SimConfig
from ..lms.node import LMSNode
from ..lms.service import FileTransferServicer, LMSServicer
from ..lms.tutoring_pool import TutoringPool
from ..proto import rpc
from ..raft import RaftConfig
from ..raft.grpc_transport import RaftServicer
from ..serving.lms_server import make_admin, make_health
from ..serving.tutoring_server import (
    TutoringService,
    make_tutoring_admin,
    make_tutoring_health,
)
from ..utils.diskfaults import DiskFaultInjector
from ..utils.faults import CampaignRunner, FaultInjector
from ..utils.guards import make_serving_watchdog
from ..utils.healthz import HealthServer
from ..utils.metrics import Metrics
from ..utils.timeline import TimelineSampler

log = logging.getLogger(__name__)

# Sim Raft timing: fast elections so transfers/restarts resolve in tens of
# milliseconds, aggressive snapshotting so the quarantine rejoin really
# exercises InstallSnapshot (the leader compacts the prefix away).
SIM_RAFT = RaftConfig(
    election_timeout_min=0.15, election_timeout_max=0.30,
    heartbeat_interval=0.05,
)
SIM_SNAPSHOT_EVERY = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class EchoEngine:
    """Deterministic tutoring stand-in with the `answer_batch` contract.

    A tiny sleep gives the latency histograms a real (but bounded)
    distribution; it runs in the batcher's executor, never on the loop.
    Speaks the real engines' `pop_program_times` contract too, so sim
    traces carry an `engine.generate` program span and the
    `engine_prog_generate` histogram fills — the SAME reap path the
    TutoringEngine exercises, not a sim-only shortcut.
    """

    # Scoring-tenant quantum size (texts per single dispatch), mirroring
    # the real engines' `score_batch_cap` property.
    score_batch_cap = 4

    def __init__(self, delay_s: float = 0.002):
        self.delay_s = delay_s
        self._prog_times: List[Tuple[str, float, float]] = []

    def answer_batch(self, prompts: List[str]) -> List[str]:
        t0, t0_unix = time.monotonic(), time.time()
        time.sleep(self.delay_s)
        self._prog_times.append(
            ("generate", t0_unix, time.monotonic() - t0)
        )
        return [f"Echo tutor: {p.splitlines()[-2][:96]}"
                if len(p.splitlines()) >= 2 else f"Echo tutor: {p[:96]}"
                for p in prompts]

    def score(self, texts: List[str]) -> List[Dict]:
        """Deterministic stand-in for the real engines' bulk-scoring
        quantum (engine/scoring.score_texts contract: logprob/tokens/
        ppl/truncated per text) — the sim's bulk-grading night runs the
        REAL admin plane, job manager, and co-scheduler against it."""
        t0, t0_unix = time.monotonic(), time.time()
        time.sleep(self.delay_s)
        self._prog_times.append(("score", t0_unix, time.monotonic() - t0))
        out = []
        for text in texts:
            n = max(1, len(text.split()))
            out.append({"logprob": -1.5 * n, "tokens": n,
                        "ppl": 4.4817, "truncated": False})
        return out

    def pop_program_times(self) -> List[Tuple[str, float, float]]:
        out, self._prog_times = self._prog_times, []
        return out


class KeywordGate:
    """Deterministic `RelevanceGate` stand-in with the same `check`
    contract — `(passes, similarity)` from query vs. assignment text.

    Token overlap (stopwords dropped, 4-char-prefix stemming) instead of
    BERT embeddings, so the workload's off-topic asks really exercise the
    gate-reject path and the `gate_pass`/`gate_reject` counters without
    paying an XLA compile. The workload's on-topic queries score >= 0.2
    against its assignment text and the off-topic ones score 0.0, so the
    threshold splits them with margin on both sides.
    """

    threshold = 0.1

    _STOPWORDS = frozenset(
        "the a an is are was of for to and or in on at by me my what how "
        "why who when where does do did it that this after under about "
        "with please i you we your".split()
    )

    def _words(self, text: str) -> set:
        return {
            w for w in (t.strip(".,?!:;-'\"()").lower()
                        for t in text.split())
            if w and w not in self._STOPWORDS
        }

    def check(self, query: str, context: str) -> tuple:
        q, c = self._words(query), self._words(context)
        if not q:
            return False, 0.0
        hits = sum(
            1 for w in q
            if w in c or (len(w) >= 4 and any(
                len(cw) >= 4 and cw[:4] == w[:4] for cw in c
            ))
        )
        sim = hits / len(q)
        return sim >= self.threshold, sim


class SimCluster:
    def __init__(self, workdir: str, cfg: SimConfig, *, nodes: int = 3):
        self.workdir = workdir
        self.cfg = cfg
        self.n_base = nodes
        self._loop = asyncio.new_event_loop()
        self._thread: Optional[threading.Thread] = None
        self._nodes: Dict[int, Dict] = {}       # guarded-by: _lock
        self._ports: Dict[int, int] = {}        # guarded-by: _lock
        self._health_ports: Dict[int, int] = {}  # guarded-by: _lock
        self._addresses: Dict[int, str] = {}    # guarded-by: _lock
        self._extra: Optional[int] = None       # guarded-by: _lock
        self._lock = threading.Lock()
        # Tutoring fleet: index -> node record; addresses pinned for the
        # cluster's lifetime like the LMS ports.
        self._tutoring: Dict[int, Dict] = {}     # guarded-by: _lock
        self._tutoring_addrs: Dict[int, str] = {}        # guarded-by: _lock
        self._tutoring_health: Dict[int, str] = {}       # guarded-by: _lock

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            for nid in range(1, self.n_base + 1):
                self._ports[nid] = _free_port()
                self._health_ports[nid] = _free_port()
                self._addresses[nid] = f"127.0.0.1:{self._ports[nid]}"
        self._thread = threading.Thread(
            target=self._loop_main, name="sim-cluster", daemon=True
        )
        self._thread.start()
        for idx in range(getattr(self.cfg, "tutoring_nodes", 1)):
            self._run(self._boot_tutoring_node(idx), timeout=120.0)
        for nid in range(1, self.n_base + 1):
            self._run(self._boot_node(nid), timeout=60.0)
        if self.wait_leader(timeout=20.0) is None:
            raise RuntimeError("sim cluster elected no leader")

    def stop(self) -> None:
        for nid in list(self._nodes):
            try:
                self._run(self._stop_node(nid), timeout=30.0)
            except Exception:
                log.exception("stopping sim node %d failed", nid)
        for idx in list(self._tutoring):
            try:
                self._run(self._stop_tutoring_node(idx), timeout=30.0)
            except Exception:
                log.exception("stopping sim tutoring node %d failed", idx)
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _loop_main(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def _run(self, coro, timeout: float):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    # ------------------------------------------------------------- topology

    def node_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._nodes)

    def client_servers(self) -> List[str]:
        with self._lock:
            return [self._addresses[n] for n in sorted(self._addresses)
                    if n <= self.n_base]

    def extra_node_id(self) -> Optional[int]:
        with self._lock:
            return self._extra

    def health_port(self, nid: int) -> int:
        with self._lock:
            return self._health_ports[nid]

    # -------------------------------------------------------- node control

    def restart_node(self, nid: int) -> None:
        self._run(self._stop_node(nid), timeout=30.0)
        self._run(self._boot_node(nid), timeout=60.0)

    def stop_node(self, nid: int) -> None:
        self._run(self._stop_node(nid), timeout=30.0)

    def spawn_extra_node(self) -> tuple:
        """Boot one more node (fresh storage) for a membership add; it
        campaigns harmlessly until the leader commits the config entry
        (the §4.2.3 vote guard keeps it from disrupting the members)."""
        with self._lock:
            nid = max(self._ports) + 1
            self._ports[nid] = _free_port()
            self._health_ports[nid] = _free_port()
            self._addresses[nid] = f"127.0.0.1:{self._ports[nid]}"
            self._extra = nid
        self._run(self._boot_node(nid), timeout=60.0)
        return nid, self._addresses[nid]

    # ----------------------------------------------------------- HTTP plane

    def _http(self, req: urllib.request.Request, timeout: float = 10.0):
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    def admin_post(self, nid: int, path: str, body: Dict) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            return self._http(req, timeout=30.0)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"admin POST {path} on node {nid} -> {e.code}: {detail}"
            ) from e

    def admin_get(self, nid: int, path: str) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}{path}", method="GET"
        )
        return self._http(req)

    def healthz(self, nid: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}/healthz", method="GET"
        )
        return self._http(req)

    def metrics_snapshot(self, nid: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}/metrics", method="GET"
        )
        return self._http(req)

    def tutoring_count(self) -> int:
        with self._lock:
            return len(self._tutoring)

    def tutoring_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._tutoring)

    def tutoring_addresses(self) -> List[str]:
        with self._lock:
            return [self._tutoring_addrs[i]
                    for i in sorted(self._tutoring_addrs)
                    if i in self._tutoring]

    def tutoring_health_addresses(self) -> List[str]:
        with self._lock:
            return [self._tutoring_health[i]
                    for i in sorted(self._tutoring_health)
                    if i in self._tutoring]

    def tutoring_health_port(self, idx: int) -> int:
        with self._lock:
            return int(self._tutoring_health[idx].rsplit(":", 1)[1])

    def tutoring_admin_post(self, idx: int, path: str, body: Dict) -> Dict:
        """POST to one tutoring node's admin plane (e.g. /admin/drain)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.tutoring_health_port(idx)}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            return self._http(req, timeout=30.0)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"tutoring admin POST {path} on node {idx} -> "
                f"{e.code}: {detail}"
            ) from e

    def tutoring_healthz(self, idx: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.tutoring_health_port(idx)}/healthz",
            method="GET",
        )
        return self._http(req)

    def spawn_tutoring_node(self) -> tuple:
        """Boot one more (echo) tutoring node for the autoscale drill;
        returns (idx, address, health_address). The LMS routers learn it
        via POST /admin/tutoring."""
        with self._lock:
            idx = (max(self._tutoring_addrs) + 1 if self._tutoring_addrs
                   else 0)
        self._run(self._boot_tutoring_node(idx, force_echo=True),
                  timeout=60.0)
        with self._lock:
            return idx, self._tutoring_addrs[idx], self._tutoring_health[idx]

    def stop_tutoring_node(self, idx: int) -> None:
        self._run(self._stop_tutoring_node(idx), timeout=30.0)

    def tutoring_node_metrics(self, idx: int) -> Dict:
        with self._lock:
            rec = self._tutoring.get(idx)
        return rec["metrics"].snapshot() if rec else {}

    def tutoring_metrics_snapshot(self) -> Dict:
        """The tutoring FLEET's serving Metrics, merged (counters
        summed, gauges maxed, histograms by worst p95) — the shape the
        SLO verdict and the telemetry "tutoring" source read. {} before
        boot/after teardown. Snapshot() is thread-safe."""
        with self._lock:
            recs = list(self._tutoring.values())
        snaps = [rec["metrics"].snapshot() for rec in recs]
        if not snaps:
            return {}
        if len(snaps) == 1:
            return snaps[0]
        merged: Dict = {"counters": {}, "gauges": {}, "latency": {}}
        for snap in snaps:
            for name, val in snap.get("counters", {}).items():
                merged["counters"][name] = (
                    merged["counters"].get(name, 0) + int(val)
                )
            for name, val in snap.get("gauges", {}).items():
                merged["gauges"][name] = max(
                    merged["gauges"].get(name, float("-inf")), float(val)
                )
            for name, block in snap.get("latency", {}).items():
                worst = merged["latency"].get(name)
                if worst is None or float(block.get("p95_s", 0.0)) > float(
                    worst.get("p95_s", 0.0)
                ):
                    merged["latency"][name] = dict(block)
        # Percentiles come from the worst node, but `count` must be the
        # fleet SUM: a per-node count would jump whenever the worst node
        # flips, and Timeline.append would misread the jumps as counter
        # resets — phantom observations in hist_rate/dcount (the same
        # rule utils/scrape.py applies to its cluster merge).
        for name, block in merged["latency"].items():
            block["count"] = float(sum(
                float(s.get("latency", {}).get(name, {}).get("count", 0))
                for s in snaps
            ))
        return merged

    def scrape_all(self) -> tuple:
        """({nid: /metrics}, {nid: /healthz}) for every live node."""
        metrics, health = {}, {}
        for nid in self.node_ids():
            try:
                metrics[nid] = self.metrics_snapshot(nid)
                health[nid] = self.healthz(nid)
            except (urllib.error.URLError, OSError) as e:
                raise RuntimeError(
                    f"node {nid} unreachable during final scrape: {e}"
                ) from e
        return metrics, health

    # --------------------------------------------------------------- waits

    def wait_leader(self, timeout: float,
                    exclude: Optional[int] = None) -> Optional[int]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for nid in self.node_ids():
                if nid == exclude:
                    continue
                try:
                    h = self.healthz(nid)
                except (urllib.error.URLError, OSError):
                    continue
                if h.get("role") == "leader" and not h.get(
                    "storage_recovering"
                ):
                    return nid
            time.sleep(0.05)
        return None

    def wait_healthy(self, nid: int, timeout: float) -> Dict:
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                h = self.healthz(nid)
                if h.get("ok"):
                    return h
            except (urllib.error.URLError, OSError) as e:
                last = e
            time.sleep(0.05)
        raise TimeoutError(f"node {nid} not healthy in {timeout}s ({last})")

    def wait_until(self, nid: int, pred: Callable[[Dict], bool],
                   timeout: float, what: str) -> Dict:
        deadline = time.monotonic() + timeout
        h: Dict = {}
        while time.monotonic() < deadline:
            try:
                h = self.healthz(nid)
                if pred(h):
                    return h
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.05)
        raise TimeoutError(f"node {nid}: timed out waiting for {what} "
                           f"(last healthz: {h})")

    # ------------------------------------------------------------ coroutines

    async def _boot_tutoring_node(self, idx: int,
                                  force_echo: bool = False) -> None:
        """One tutoring fleet member: real gRPC server + the SAME
        healthz/drain admin plane the production entrypoint serves
        (make_tutoring_health/make_tutoring_admin). Node 0 runs the
        configured engine; extra members (and autoscale spawns) run the
        echo stand-in so a 3-node fleet costs no extra XLA compiles."""
        from ..engine import BatchingQueue, PagedQueue, ScoringManager

        queue = None
        metrics = Metrics()
        scorer = None
        if (self.cfg.tutoring_engine in ("tiny", "tiny-paged")
                and idx == 0 and not force_echo):
            import jax

            from ..engine import (
                EngineConfig,
                PagedEngine,
                SamplingParams,
                TutoringEngine,
            )

            config = EngineConfig(
                model="tiny",
                sampling=SamplingParams(max_new_tokens=8),
                length_buckets=(32,), batch_buckets=(1, 2, 4),
                dtype=jax.numpy.float32,
                # Bulk-grading night runs against the REAL score path:
                # warmup covers the score domain so the mid-run job
                # compiles nothing live.
                scoring=self.cfg.bulk_scoring,
            )
            if self.cfg.tutoring_engine == "tiny-paged":
                # The real serving configuration scaled down: paged
                # continuous batching with the shared-prefix radix
                # cache, so a concentrated same-course workload
                # (`course_concentration` > 0) produces a measurable
                # prefix_cache_hit_rate in the soak's verdict. Two
                # prompt buckets + 8-token blocks: the tiny position
                # table caps prompts at 32 tokens, and a partial
                # prefill needs a suffix bucket that leaves at least
                # one whole block of prefix in the window. NOTE the
                # 32-token cap also tail-truncates the long course
                # context, so at this scale hits come from students
                # repeating the same course question verbatim — real
                # lookup/splice/partial-prefill traffic, but not
                # cross-question context sharing (that is bench.py's
                # shared-prefix scenario, with token-level control).
                import dataclasses as _dc

                engine = PagedEngine(
                    _dc.replace(config, length_buckets=(16, 32)),
                    slots=4, chunk=4, prefix_cache=True,
                    prefix_cache_blocks=128, prefix_block_tokens=8,
                    # Fused stall-free admission, like cluster.toml: the
                    # soak exercises staged chunked prefill under real
                    # diurnal churn (decode_stalled_tokens stays 0).
                    prefill_chunk_tokens=8,
                )
                if self.cfg.bulk_scoring:
                    scorer = ScoringManager(engine, metrics=metrics,
                                            max_job_texts=1024,
                                            jobs_retained=8)
                queue = PagedQueue(engine, metrics=metrics, max_queue=64,
                                   scorer=scorer)
            else:
                engine = TutoringEngine(config)
            # Compile now, while this loop runs nothing else: tutoring
            # boots BEFORE the Raft nodes, so the XLA compile can't stall
            # their tick loops (every node shares this loop+GIL).
            if queue is not None:
                engine.warmup()
            else:
                engine.warmup(batch=4)
        else:
            engine = EchoEngine()
        if self.cfg.bulk_scoring and scorer is None:
            # Every fleet member runs the background scoring tenant: the
            # bulk-grading night lands on whichever node the LMS router's
            # background route picks (the coldest one).
            scorer = ScoringManager(engine, metrics=metrics,
                                    max_job_texts=1024, jobs_retained=8)
        if queue is None:
            queue = BatchingQueue(engine, max_batch=4, max_wait_ms=5.0,
                                  metrics=metrics, max_queue=64,
                                  scorer=scorer)
        await queue.start()
        server = grpc.aio.server()
        service = TutoringService(queue, metrics, node_id=f"tut{idx}")
        rpc.add_TutoringServicer_to_server(service, server)
        with self._lock:
            want = self._tutoring_addrs.get(idx)
        if want is not None:
            port = server.add_insecure_port(want)
        else:
            port = server.add_insecure_port("127.0.0.1:0")
        await server.start()

        async def tutoring_admin_get(path: str,
                                     _scorer=scorer) -> Dict:
            # GET /admin/score[/<job-id>]: the scoring tenant's job list
            # / one job's progress+results — the same read surface the
            # production entrypoint serves.
            from ..engine.scoring import score_admin_get

            return score_admin_get(path, _scorer)

        health = HealthServer(
            metrics,
            health=make_tutoring_health(service, queue,
                                        type(engine).__name__, 64,
                                        scorer=scorer),
            admin=make_tutoring_admin(service, scorer=scorer),
            admin_get=tutoring_admin_get,
            port=(self.tutoring_health_port(idx) if want is not None
                  else 0),
        )
        hport = await health.start()
        with self._lock:
            self._tutoring[idx] = {
                "server": server, "queue": queue, "metrics": metrics,
                "service": service, "health": health,
            }
            self._tutoring_addrs[idx] = f"127.0.0.1:{port}"
            self._tutoring_health[idx] = f"127.0.0.1:{hport}"

    async def _stop_tutoring_node(self, idx: int) -> None:
        with self._lock:
            rec = self._tutoring.pop(idx, None)
        if rec is None:
            return
        await rec["health"].stop()
        await rec["server"].stop(None)
        await rec["queue"].close()

    async def _boot_node(self, nid: int) -> None:
        cfg = self.cfg
        with self._lock:
            addresses = dict(self._addresses)
            port = self._ports[nid]
        faults = FaultInjector(seed=cfg.seed * 1000 + nid)
        disk_faults = DiskFaultInjector(seed=cfg.seed * 1000 + nid)
        metrics = Metrics()
        lms_node = LMSNode(
            nid, addresses, f"{self.workdir}/node{nid}",
            raft_config=SIM_RAFT, snapshot_every=SIM_SNAPSHOT_EVERY,
            fault_injector=faults, disk_fault_injector=disk_faults,
            metrics=metrics,
        )
        # The tutoring routing tier, fleet-sized to [sim] tutoring_nodes:
        # sim-scale spill/hedge/warm-up knobs so the drills resolve
        # inside a seconds-long run (hedge after 100 ms, 1 s warm-up,
        # 200 ms health polls driving drain ejection/rejoin).
        pool = TutoringPool(
            self.tutoring_addresses(),
            metrics=metrics,
            health_addresses=self.tutoring_health_addresses(),
            fault_injector=faults,
            breaker_failure_threshold=3,
            breaker_recovery_s=0.5,
            timeout_s=min(30.0, cfg.llm_budget_s),
            deadline_floor_s=0.25,
            hedge_after_s=0.1,
            queue_spill_depth=16,
            warmup_s=1.0,
            health_poll_s=0.2,
        )
        servicer = LMSServicer(
            lms_node.node, lms_node.state, lms_node.blobs,
            gate=KeywordGate(),
            metrics=metrics,
            peer_addresses=lms_node.addresses,
            self_id=nid,
            fault_injector=faults,
            tutoring_timeout_s=min(30.0, cfg.llm_budget_s),
            deadline_floor_s=0.25,
            tutoring_pool=pool,
        )
        server = grpc.aio.server(
            options=[("grpc.max_receive_message_length", 50 * 1024 * 1024)]
        )
        rpc.add_LMSServicer_to_server(servicer, server)
        rpc.add_RaftServiceServicer_to_server(
            # Live map: membership-added peers must be reported by
            # GetLeader (client leader-hint re-discovery depends on it).
            RaftServicer(lms_node.node, lms_node.addresses,
                         kv=lms_node.state.data["kv"]),
            server,
        )
        rpc.add_FileTransferServiceServicer_to_server(
            FileTransferServicer(lms_node.blobs), server
        )
        bound = server.add_insecure_port(f"127.0.0.1:{port}")
        if bound != port:
            raise RuntimeError(f"node {nid}: wanted port {port}, got {bound}")
        await server.start()
        await lms_node.start()
        campaigns = CampaignRunner(faults, disk_faults, metrics=metrics)
        # Same node-local telemetry timeline the production entrypoint
        # samples, served at GET /admin/timeline per node.
        sampler = TimelineSampler(metrics, interval_s=0.5,
                                  max_points=256).start()
        # The router's drain-aware health poller, like the production
        # entrypoint starts.
        pool.start()
        admin, admin_get = make_admin(lms_node, faults, disk_faults,
                                      campaigns,
                                      timeline=sampler.timeline,
                                      pool=pool)
        health = HealthServer(
            metrics,
            health=make_health(nid, lms_node, pool, faults),
            admin=admin, admin_get=admin_get,
            port=self._health_ports[nid],
        )
        await health.start()
        # Same serving-loop heartbeat the production entrypoint runs, so
        # the sim's SLO scrape sees serving_tick_lag/-_stalls per node.
        watchdog = asyncio.get_running_loop().create_task(
            make_serving_watchdog(metrics).run()
        )
        with self._lock:
            self._nodes[nid] = {
                "lms_node": lms_node, "server": server, "health": health,
                "faults": faults, "disk_faults": disk_faults,
                "campaigns": campaigns, "metrics": metrics,
                "pool": pool, "watchdog": watchdog,
                "sampler": sampler,
            }

    async def _stop_node(self, nid: int) -> None:
        with self._lock:
            rec = self._nodes.pop(nid, None)
        if rec is None:
            return
        rec["campaigns"].cancel()
        rec["watchdog"].cancel()
        rec["sampler"].stop()
        await rec["pool"].close()
        await rec["health"].stop()
        await rec["lms_node"].stop()
        await rec["server"].stop(None)
