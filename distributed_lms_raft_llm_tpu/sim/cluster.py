"""The cluster under test: real gRPC nodes with the real admin plane.

Boots N LMS nodes (Raft + LMS + FileTransfer servicers, per-node fault
injectors, breaker, and the SAME admin/health plane `serving/lms_server`
serves — `make_admin`/`make_health` are imported, not re-implemented) plus
a tutoring node, all on one background asyncio loop, with thread-safe
control methods for the workload workers and the operations scheduler:
restart a node in place (same port, same data dir — the storage-recovery
path runs for real), spawn an extra node for a membership add, scrape
`/metrics`, and drive `POST`/`GET /admin/*` over actual HTTP.

Ports are allocated once and pinned for the cluster's lifetime so a
restarted node comes back at its advertised address (peers re-dial it,
clients re-discover it).

The default tutoring engine is `EchoEngine` — a wire-complete stand-in
that exercises the REAL BatchingQueue admission, deadline shedding, HMAC
path, and gRPC plumbing without paying an XLA compile; the tier-2 soak
swaps in the real tiny JAX engine (`[sim] tutoring_engine = "tiny"`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from ..config import SimConfig
from ..lms.group_router import (
    ROUTING_MAP_KEY,
    GroupsAdmin,
    ReshardCoordinator,
    RoutedLMSServicer,
    RoutingMap,
)
from ..lms.node import LMSNode
from ..lms.service import FileTransferServicer, LMSServicer
from ..lms.tutoring_pool import TutoringPool
from ..proto import rpc
from ..raft import NotLeader, RaftConfig, encode_command
from ..raft.grpc_transport import RaftServicer
from ..serving.lms_server import make_admin, make_health
from ..serving.tutoring_server import (
    TutoringService,
    make_tutoring_admin,
    make_tutoring_health,
)
from ..utils.diskfaults import DiskFaultInjector
from ..utils.faults import CampaignRunner, FaultInjector
from ..utils.guards import make_serving_watchdog
from ..utils.healthz import HealthServer
from ..utils.metrics import Metrics
from ..utils.timeline import TimelineSampler
from .workload import WorkloadGenerator

log = logging.getLogger(__name__)

# Sim Raft timing: fast elections so transfers/restarts resolve in tens of
# milliseconds, aggressive snapshotting so the quarantine rejoin really
# exercises InstallSnapshot (the leader compacts the prefix away).
SIM_RAFT = RaftConfig(
    election_timeout_min=0.15, election_timeout_max=0.30,
    heartbeat_interval=0.05,
)
SIM_SNAPSHOT_EVERY = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class EchoEngine:
    """Deterministic tutoring stand-in with the `answer_batch` contract.

    A tiny sleep gives the latency histograms a real (but bounded)
    distribution; it runs in the batcher's executor, never on the loop.
    Speaks the real engines' `pop_program_times` contract too, so sim
    traces carry an `engine.generate` program span and the
    `engine_prog_generate` histogram fills — the SAME reap path the
    TutoringEngine exercises, not a sim-only shortcut.
    """

    # Scoring-tenant quantum size (texts per single dispatch), mirroring
    # the real engines' `score_batch_cap` property.
    score_batch_cap = 4

    def __init__(self, delay_s: float = 0.002):
        self.delay_s = delay_s
        self._prog_times: List[Tuple[str, float, float]] = []

    def answer_batch(self, prompts: List[str]) -> List[str]:
        t0, t0_unix = time.monotonic(), time.time()
        time.sleep(self.delay_s)
        self._prog_times.append(
            ("generate", t0_unix, time.monotonic() - t0)
        )
        return [f"Echo tutor: {p.splitlines()[-2][:96]}"
                if len(p.splitlines()) >= 2 else f"Echo tutor: {p[:96]}"
                for p in prompts]

    def score(self, texts: List[str]) -> List[Dict]:
        """Deterministic stand-in for the real engines' bulk-scoring
        quantum (engine/scoring.score_texts contract: logprob/tokens/
        ppl/truncated per text) — the sim's bulk-grading night runs the
        REAL admin plane, job manager, and co-scheduler against it."""
        t0, t0_unix = time.monotonic(), time.time()
        time.sleep(self.delay_s)
        self._prog_times.append(("score", t0_unix, time.monotonic() - t0))
        out = []
        for text in texts:
            n = max(1, len(text.split()))
            out.append({"logprob": -1.5 * n, "tokens": n,
                        "ppl": 4.4817, "truncated": False})
        return out

    def pop_program_times(self) -> List[Tuple[str, float, float]]:
        out, self._prog_times = self._prog_times, []
        return out


class KeywordGate:
    """Deterministic `RelevanceGate` stand-in with the same `check`
    contract — `(passes, similarity)` from query vs. assignment text.

    Token overlap (stopwords dropped, 4-char-prefix stemming) instead of
    BERT embeddings, so the workload's off-topic asks really exercise the
    gate-reject path and the `gate_pass`/`gate_reject` counters without
    paying an XLA compile. The workload's on-topic queries score >= 0.2
    against its assignment text and the off-topic ones score 0.0, so the
    threshold splits them with margin on both sides.
    """

    threshold = 0.1

    _STOPWORDS = frozenset(
        "the a an is are was of for to and or in on at by me my what how "
        "why who when where does do did it that this after under about "
        "with please i you we your".split()
    )

    def _words(self, text: str) -> set:
        return {
            w for w in (t.strip(".,?!:;-'\"()").lower()
                        for t in text.split())
            if w and w not in self._STOPWORDS
        }

    def check(self, query: str, context: str) -> tuple:
        q, c = self._words(query), self._words(context)
        if not q:
            return False, 0.0
        hits = sum(
            1 for w in q
            if w in c or (len(w) >= 4 and any(
                len(cw) >= 4 and cw[:4] == w[:4] for cw in c
            ))
        )
        sim = hits / len(q)
        return sim >= self.threshold, sim


class SimCluster:
    def __init__(self, workdir: str, cfg: SimConfig, *, nodes: int = 3):
        self.workdir = workdir
        self.cfg = cfg
        self.n_base = nodes
        self._loop = asyncio.new_event_loop()
        self._thread: Optional[threading.Thread] = None
        self._nodes: Dict[int, Dict] = {}       # guarded-by: _lock
        self._ports: Dict[int, int] = {}        # guarded-by: _lock
        self._health_ports: Dict[int, int] = {}  # guarded-by: _lock
        self._addresses: Dict[int, str] = {}    # guarded-by: _lock
        self._extra: Optional[int] = None       # guarded-by: _lock
        self._lock = threading.Lock()
        # Tutoring fleet: index -> node record; addresses pinned for the
        # cluster's lifetime like the LMS ports.
        self._tutoring: Dict[int, Dict] = {}     # guarded-by: _lock
        self._tutoring_addrs: Dict[int, str] = {}        # guarded-by: _lock
        self._tutoring_health: Dict[int, str] = {}       # guarded-by: _lock
        # Sharded control plane ([sim] lms_groups > 1): per-(group, node)
        # Raft ports, pinned like the base ports so restarts come back at
        # the same advertised address.
        self._group_ports: Dict[Tuple[int, int], int] = {}  # guarded-by: _lock
        # The workload's static course assignment doubles as the router's
        # course_of — routing map and traffic agree on who lives where.
        self._wgen = WorkloadGenerator(cfg)
        self._initial_map = RoutingMap.initial(
            max(1, cfg.lms_groups), self._wgen.courses
        )
        # One router HMAC key per cluster ([groups] secret in a real
        # deployment): routers sign forwarded x-lms-* control metadata
        # with it, so a simulated hostile client cannot forge group
        # targeting or forced auth salts/tokens.
        self._router_secret = secrets.token_hex(16)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            for nid in range(1, self.n_base + 1):
                self._ports[nid] = _free_port()
                self._health_ports[nid] = _free_port()
                self._addresses[nid] = f"127.0.0.1:{self._ports[nid]}"
        self._thread = threading.Thread(
            target=self._loop_main, name="sim-cluster", daemon=True
        )
        self._thread.start()
        for idx in range(getattr(self.cfg, "tutoring_nodes", 1)):
            self._run(self._boot_tutoring_node(idx), timeout=120.0)
        for nid in range(1, self.n_base + 1):
            self._run(self._boot_node(nid), timeout=60.0)
        if self.wait_leader(timeout=20.0) is None:
            raise RuntimeError("sim cluster elected no leader")
        for gid in range(1, self.group_count()):
            if self.wait_group_leader(gid, timeout=20.0) is None:
                raise RuntimeError(f"raft group {gid} elected no leader")

    def stop(self) -> None:
        for nid in list(self._nodes):
            try:
                self._run(self._stop_node(nid), timeout=30.0)
            except Exception:
                log.exception("stopping sim node %d failed", nid)
        for idx in list(self._tutoring):
            try:
                self._run(self._stop_tutoring_node(idx), timeout=30.0)
            except Exception:
                log.exception("stopping sim tutoring node %d failed", idx)
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _loop_main(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def _run(self, coro, timeout: float):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    # ------------------------------------------------------------- topology

    def node_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._nodes)

    def client_servers(self) -> List[str]:
        with self._lock:
            return [self._addresses[n] for n in sorted(self._addresses)
                    if n <= self.n_base]

    def extra_node_id(self) -> Optional[int]:
        with self._lock:
            return self._extra

    def health_port(self, nid: int) -> int:
        with self._lock:
            return self._health_ports[nid]

    # ------------------------------------------------------- group topology

    def group_count(self) -> int:
        return max(1, self.cfg.lms_groups)

    def course_of(self, actor: str) -> str:
        return self._wgen.course_of(actor)

    def group_of(self, actor: str) -> int:
        """Static hint-lane assignment for clients (the INITIAL map —
        lanes only partition the leader-hint cache, so a post-reshard
        client landing on its old lane is merely a cold cache, never a
        correctness issue; the router re-routes every request against
        the live replicated map)."""
        if self.group_count() <= 1:
            return 0
        return self._initial_map.group_for(actor, self._wgen.course_of)

    def live_group_of(self, actor: str) -> int:
        """`actor`'s owning group per the LIVE replicated routing map
        (falls back to the initial map before the first flip) — what the
        ledger tags acked writes with, so the audit knows which writes
        crossed a resharding boundary."""
        if self.group_count() <= 1:
            return 0
        raw = None
        with self._lock:
            recs = list(self._nodes.values())
        for rec in recs:
            gnode = rec.get("groups", {}).get(0)
            if gnode is None:
                continue
            candidate = gnode.state.data["kv"].get(ROUTING_MAP_KEY)
            if candidate:
                raw = candidate
                if gnode.node.is_leader:
                    break
        m = RoutingMap.from_json(raw) if raw else self._initial_map
        return m.group_for(actor, self._wgen.course_of)

    def _group_addrs_locked(self, gid: int) -> Dict[int, str]:  # guarded-by: _lock
        """Pin (allocate-once) group `gid`'s Raft port for every known
        node id. Caller holds `_lock`."""
        out: Dict[int, str] = {}
        for nid in self._addresses:
            key = (gid, nid)
            if key not in self._group_ports:
                self._group_ports[key] = _free_port()
            out[nid] = f"127.0.0.1:{self._group_ports[key]}"
        return out

    def group_topology(self, nid: int) -> Dict:
        """GET /admin/raft on one node — the routing map + per-group
        members/leader/term/applied rows the dashboard renders."""
        return self.admin_get(nid, "/admin/raft")

    def group_leader(self, gid: int) -> Optional[int]:
        for nid in self.node_ids():
            with self._lock:
                rec = self._nodes.get(nid)
            if rec is None:
                continue
            gnode = rec.get("groups", {}).get(gid)
            if gnode is not None and gnode.node.is_leader:
                return nid
        return None

    def wait_group_leader(self, gid: int, timeout: float) -> Optional[int]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            nid = self.group_leader(gid)
            if nid is not None:
                return nid
            time.sleep(0.05)
        return None

    def routing_map_doc(self, nid: Optional[int] = None) -> Dict:
        target = nid if nid is not None else self.node_ids()[0]
        return dict(self.group_topology(target).get("routing_map", {}))

    def reshard(self, course: str, to_group: int) -> Dict:
        """Drive a live course split through the REAL admin plane (the
        coordinator journals every step in the meta group)."""
        nid = self.wait_leader(timeout=15.0)
        if nid is None:
            raise RuntimeError("no leader to accept /admin/reshard")
        return self.admin_post(nid, "/admin/reshard",
                               {"course": course, "to_group": to_group})

    # -------------------------------------------------------- node control

    def restart_node(self, nid: int) -> None:
        self._run(self._stop_node(nid), timeout=30.0)
        self._run(self._boot_node(nid), timeout=60.0)

    def stop_node(self, nid: int) -> None:
        self._run(self._stop_node(nid), timeout=30.0)

    def spawn_extra_node(self) -> tuple:
        """Boot one more node (fresh storage) for a membership add; it
        campaigns harmlessly until the leader commits the config entry
        (the §4.2.3 vote guard keeps it from disrupting the members)."""
        with self._lock:
            nid = max(self._ports) + 1
            self._ports[nid] = _free_port()
            self._health_ports[nid] = _free_port()
            self._addresses[nid] = f"127.0.0.1:{self._ports[nid]}"
            self._extra = nid
        self._run(self._boot_node(nid), timeout=60.0)
        return nid, self._addresses[nid]

    # ----------------------------------------------------------- HTTP plane

    def _http(self, req: urllib.request.Request, timeout: float = 10.0):
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    def admin_post(self, nid: int, path: str, body: Dict) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            return self._http(req, timeout=30.0)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"admin POST {path} on node {nid} -> {e.code}: {detail}"
            ) from e

    def admin_get(self, nid: int, path: str) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}{path}", method="GET"
        )
        return self._http(req)

    def healthz(self, nid: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}/healthz", method="GET"
        )
        return self._http(req)

    def metrics_snapshot(self, nid: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.health_port(nid)}/metrics", method="GET"
        )
        return self._http(req)

    def tutoring_count(self) -> int:
        with self._lock:
            return len(self._tutoring)

    def tutoring_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._tutoring)

    def tutoring_addresses(self) -> List[str]:
        with self._lock:
            return [self._tutoring_addrs[i]
                    for i in sorted(self._tutoring_addrs)
                    if i in self._tutoring]

    def tutoring_health_addresses(self) -> List[str]:
        with self._lock:
            return [self._tutoring_health[i]
                    for i in sorted(self._tutoring_health)
                    if i in self._tutoring]

    def tutoring_health_port(self, idx: int) -> int:
        with self._lock:
            return int(self._tutoring_health[idx].rsplit(":", 1)[1])

    def tutoring_admin_post(self, idx: int, path: str, body: Dict) -> Dict:
        """POST to one tutoring node's admin plane (e.g. /admin/drain)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.tutoring_health_port(idx)}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            return self._http(req, timeout=30.0)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(
                f"tutoring admin POST {path} on node {idx} -> "
                f"{e.code}: {detail}"
            ) from e

    def tutoring_healthz(self, idx: int) -> Dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.tutoring_health_port(idx)}/healthz",
            method="GET",
        )
        return self._http(req)

    def spawn_tutoring_node(self) -> tuple:
        """Boot one more (echo) tutoring node for the autoscale drill;
        returns (idx, address, health_address). The LMS routers learn it
        via POST /admin/tutoring."""
        with self._lock:
            idx = (max(self._tutoring_addrs) + 1 if self._tutoring_addrs
                   else 0)
        self._run(self._boot_tutoring_node(idx, force_echo=True),
                  timeout=60.0)
        with self._lock:
            return idx, self._tutoring_addrs[idx], self._tutoring_health[idx]

    def stop_tutoring_node(self, idx: int) -> None:
        self._run(self._stop_tutoring_node(idx), timeout=30.0)

    def tutoring_node_metrics(self, idx: int) -> Dict:
        with self._lock:
            rec = self._tutoring.get(idx)
        return rec["metrics"].snapshot() if rec else {}

    def tutoring_metrics_snapshot(self) -> Dict:
        """The tutoring FLEET's serving Metrics, merged (counters
        summed, gauges maxed, histograms by worst p95) — the shape the
        SLO verdict and the telemetry "tutoring" source read. {} before
        boot/after teardown. Snapshot() is thread-safe."""
        with self._lock:
            recs = list(self._tutoring.values())
        snaps = [rec["metrics"].snapshot() for rec in recs]
        if not snaps:
            return {}
        if len(snaps) == 1:
            return snaps[0]
        merged: Dict = {"counters": {}, "gauges": {}, "latency": {}}
        for snap in snaps:
            for name, val in snap.get("counters", {}).items():
                merged["counters"][name] = (
                    merged["counters"].get(name, 0) + int(val)
                )
            for name, val in snap.get("gauges", {}).items():
                merged["gauges"][name] = max(
                    merged["gauges"].get(name, float("-inf")), float(val)
                )
            for name, block in snap.get("latency", {}).items():
                worst = merged["latency"].get(name)
                if worst is None or float(block.get("p95_s", 0.0)) > float(
                    worst.get("p95_s", 0.0)
                ):
                    merged["latency"][name] = dict(block)
        # Percentiles come from the worst node, but `count` must be the
        # fleet SUM: a per-node count would jump whenever the worst node
        # flips, and Timeline.append would misread the jumps as counter
        # resets — phantom observations in hist_rate/dcount (the same
        # rule utils/scrape.py applies to its cluster merge).
        for name, block in merged["latency"].items():
            block["count"] = float(sum(
                float(s.get("latency", {}).get(name, {}).get("count", 0))
                for s in snaps
            ))
        return merged

    def scrape_all(self) -> tuple:
        """({nid: /metrics}, {nid: /healthz}) for every live node."""
        metrics, health = {}, {}
        for nid in self.node_ids():
            try:
                metrics[nid] = self.metrics_snapshot(nid)
                health[nid] = self.healthz(nid)
            except (urllib.error.URLError, OSError) as e:
                raise RuntimeError(
                    f"node {nid} unreachable during final scrape: {e}"
                ) from e
        return metrics, health

    # --------------------------------------------------------------- waits

    def wait_leader(self, timeout: float,
                    exclude: Optional[int] = None) -> Optional[int]:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for nid in self.node_ids():
                if nid == exclude:
                    continue
                try:
                    h = self.healthz(nid)
                except (urllib.error.URLError, OSError):
                    continue
                if h.get("role") == "leader" and not h.get(
                    "storage_recovering"
                ):
                    return nid
            time.sleep(0.05)
        return None

    def wait_healthy(self, nid: int, timeout: float) -> Dict:
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                h = self.healthz(nid)
                if h.get("ok"):
                    return h
            except (urllib.error.URLError, OSError) as e:
                last = e
            time.sleep(0.05)
        raise TimeoutError(f"node {nid} not healthy in {timeout}s ({last})")

    def wait_until(self, nid: int, pred: Callable[[Dict], bool],
                   timeout: float, what: str) -> Dict:
        deadline = time.monotonic() + timeout
        h: Dict = {}
        while time.monotonic() < deadline:
            try:
                h = self.healthz(nid)
                if pred(h):
                    return h
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.05)
        raise TimeoutError(f"node {nid}: timed out waiting for {what} "
                           f"(last healthz: {h})")

    # ------------------------------------------------------------ coroutines

    async def _boot_tutoring_node(self, idx: int,
                                  force_echo: bool = False) -> None:
        """One tutoring fleet member: real gRPC server + the SAME
        healthz/drain admin plane the production entrypoint serves
        (make_tutoring_health/make_tutoring_admin). Node 0 runs the
        configured engine; extra members (and autoscale spawns) run the
        echo stand-in so a 3-node fleet costs no extra XLA compiles."""
        from ..engine import BatchingQueue, PagedQueue, ScoringManager

        queue = None
        metrics = Metrics()
        scorer = None
        if (self.cfg.tutoring_engine in ("tiny", "tiny-paged")
                and idx == 0 and not force_echo):
            import jax

            from ..engine import (
                EngineConfig,
                PagedEngine,
                SamplingParams,
                TutoringEngine,
            )

            config = EngineConfig(
                model="tiny",
                sampling=SamplingParams(max_new_tokens=8),
                length_buckets=(32,), batch_buckets=(1, 2, 4),
                dtype=jax.numpy.float32,
                # Bulk-grading night runs against the REAL score path:
                # warmup covers the score domain so the mid-run job
                # compiles nothing live.
                scoring=self.cfg.bulk_scoring,
            )
            if self.cfg.tutoring_engine == "tiny-paged":
                # The real serving configuration scaled down: paged
                # continuous batching with the shared-prefix radix
                # cache, so a concentrated same-course workload
                # (`course_concentration` > 0) produces a measurable
                # prefix_cache_hit_rate in the soak's verdict. Two
                # prompt buckets + 8-token blocks: the tiny position
                # table caps prompts at 32 tokens, and a partial
                # prefill needs a suffix bucket that leaves at least
                # one whole block of prefix in the window. NOTE the
                # 32-token cap also tail-truncates the long course
                # context, so at this scale hits come from students
                # repeating the same course question verbatim — real
                # lookup/splice/partial-prefill traffic, but not
                # cross-question context sharing (that is bench.py's
                # shared-prefix scenario, with token-level control).
                import dataclasses as _dc

                engine = PagedEngine(
                    _dc.replace(config, length_buckets=(16, 32)),
                    slots=4, chunk=4, prefix_cache=True,
                    prefix_cache_blocks=128, prefix_block_tokens=8,
                    # Fused stall-free admission, like cluster.toml: the
                    # soak exercises staged chunked prefill under real
                    # diurnal churn (decode_stalled_tokens stays 0).
                    prefill_chunk_tokens=8,
                )
                if self.cfg.bulk_scoring:
                    scorer = ScoringManager(engine, metrics=metrics,
                                            max_job_texts=1024,
                                            jobs_retained=8)
                queue = PagedQueue(engine, metrics=metrics, max_queue=64,
                                   scorer=scorer)
            else:
                engine = TutoringEngine(config)
            # Compile now, while this loop runs nothing else: tutoring
            # boots BEFORE the Raft nodes, so the XLA compile can't stall
            # their tick loops (every node shares this loop+GIL).
            if queue is not None:
                engine.warmup()
            else:
                engine.warmup(batch=4)
        else:
            engine = EchoEngine()
        if self.cfg.bulk_scoring and scorer is None:
            # Every fleet member runs the background scoring tenant: the
            # bulk-grading night lands on whichever node the LMS router's
            # background route picks (the coldest one).
            scorer = ScoringManager(engine, metrics=metrics,
                                    max_job_texts=1024, jobs_retained=8)
        if queue is None:
            queue = BatchingQueue(engine, max_batch=4, max_wait_ms=5.0,
                                  metrics=metrics, max_queue=64,
                                  scorer=scorer)
        await queue.start()
        server = grpc.aio.server()
        service = TutoringService(queue, metrics, node_id=f"tut{idx}",
                                  session_ttl_s=self.cfg.session_ttl_s,
                                  session_max=64)
        rpc.add_TutoringServicer_to_server(service, server)
        with self._lock:
            want = self._tutoring_addrs.get(idx)
        if want is not None:
            port = server.add_insecure_port(want)
        else:
            port = server.add_insecure_port("127.0.0.1:0")
        await server.start()

        async def tutoring_admin_get(path: str,
                                     _scorer=scorer) -> Dict:
            # GET /admin/score[/<job-id>]: the scoring tenant's job list
            # / one job's progress+results — the same read surface the
            # production entrypoint serves.
            from ..engine.scoring import score_admin_get

            return score_admin_get(path, _scorer)

        health = HealthServer(
            metrics,
            health=make_tutoring_health(service, queue,
                                        type(engine).__name__, 64,
                                        scorer=scorer),
            admin=make_tutoring_admin(service, scorer=scorer),
            admin_get=tutoring_admin_get,
            port=(self.tutoring_health_port(idx) if want is not None
                  else 0),
        )
        hport = await health.start()
        with self._lock:
            self._tutoring[idx] = {
                "server": server, "queue": queue, "metrics": metrics,
                "service": service, "health": health,
            }
            self._tutoring_addrs[idx] = f"127.0.0.1:{port}"
            self._tutoring_health[idx] = f"127.0.0.1:{hport}"

    async def _stop_tutoring_node(self, idx: int) -> None:
        with self._lock:
            rec = self._tutoring.pop(idx, None)
        if rec is None:
            return
        await rec["health"].stop()
        await rec["server"].stop(None)
        await rec["queue"].close()

    async def _boot_node(self, nid: int) -> None:
        cfg = self.cfg
        with self._lock:
            addresses = dict(self._addresses)
            port = self._ports[nid]
        faults = FaultInjector(seed=cfg.seed * 1000 + nid)
        disk_faults = DiskFaultInjector(seed=cfg.seed * 1000 + nid)
        metrics = Metrics()
        lms_node = LMSNode(
            nid, addresses, f"{self.workdir}/node{nid}",
            raft_config=SIM_RAFT, snapshot_every=SIM_SNAPSHOT_EVERY,
            fault_injector=faults, disk_fault_injector=disk_faults,
            metrics=metrics,
        )
        # Sharded control plane: group 0 IS the base node (meta group +
        # byte-compatible data group); gids >= 1 are extra Raft groups on
        # this node with their own ports/WALs. They share the node's blob
        # store and fault injector — their chaos namespace is `raft:<gid>`
        # so a campaign can sever ONE group's quorum links while the
        # others keep serving.
        groups: Dict[int, LMSNode] = {0: lms_node}
        if cfg.lms_groups > 1:
            with self._lock:
                group_addrs = {
                    gid: self._group_addrs_locked(gid)
                    for gid in range(1, cfg.lms_groups)
                }
            for gid in range(1, cfg.lms_groups):
                groups[gid] = LMSNode(
                    nid, group_addrs[gid],
                    f"{self.workdir}/node{nid}/group{gid}",
                    raft_config=SIM_RAFT,
                    snapshot_every=SIM_SNAPSHOT_EVERY,
                    fault_injector=faults,
                    disk_fault_injector=disk_faults,
                    metrics=metrics,
                    blobs=lms_node.blobs,
                    blob_addresses=lms_node.addresses,
                    fault_prefix=f"raft:{gid}",
                )
        # The tutoring routing tier, fleet-sized to [sim] tutoring_nodes:
        # sim-scale spill/hedge/warm-up knobs so the drills resolve
        # inside a seconds-long run (hedge after 100 ms, 1 s warm-up,
        # 200 ms health polls driving drain ejection/rejoin).
        pool = TutoringPool(
            self.tutoring_addresses(),
            metrics=metrics,
            health_addresses=self.tutoring_health_addresses(),
            fault_injector=faults,
            breaker_failure_threshold=3,
            breaker_recovery_s=0.5,
            timeout_s=min(30.0, cfg.llm_budget_s),
            deadline_floor_s=0.25,
            hedge_after_s=0.1,
            stream_stall_s=1.0,
            queue_spill_depth=16,
            warmup_s=1.0,
            health_poll_s=0.2,
        )
        def _servicer(gnode: LMSNode) -> LMSServicer:
            return LMSServicer(
                gnode.node, gnode.state, lms_node.blobs,
                gate=KeywordGate(),
                metrics=metrics,
                peer_addresses=lms_node.addresses,
                self_id=nid,
                fault_injector=faults,
                tutoring_timeout_s=min(30.0, cfg.llm_budget_s),
                deadline_floor_s=0.25,
                tutoring_pool=pool,
            )

        servicer = _servicer(lms_node)
        server = grpc.aio.server(
            options=[("grpc.max_receive_message_length", 50 * 1024 * 1024)]
        )
        router: Optional[RoutedLMSServicer] = None
        if cfg.lms_groups > 1:
            inner = {gid: (servicer if gid == 0 else _servicer(gnode))
                     for gid, gnode in groups.items()}
            router = RoutedLMSServicer(
                groups, inner, lms_node.addresses, nid,
                course_of=self._wgen.course_of,
                initial_map=self._initial_map,
                metrics=metrics,
                router_secret=self._router_secret,
            )
            rpc.add_LMSServicer_to_server(router, server)
        else:
            rpc.add_LMSServicer_to_server(servicer, server)
        rpc.add_RaftServiceServicer_to_server(
            # Live map: membership-added peers must be reported by
            # GetLeader (client leader-hint re-discovery depends on it).
            RaftServicer(lms_node.node, lms_node.addresses,
                         kv=lms_node.state.data["kv"]),
            server,
        )
        rpc.add_FileTransferServiceServicer_to_server(
            FileTransferServicer(lms_node.blobs), server
        )
        bound = server.add_insecure_port(f"127.0.0.1:{port}")
        if bound != port:
            raise RuntimeError(f"node {nid}: wanted port {port}, got {bound}")
        await server.start()
        # Per-group Raft wire: one small gRPC server per extra group (the
        # proto carries no group id, so each group needs its own port).
        # Servers come up before any group node starts campaigning.
        group_servers: Dict[int, grpc.aio.Server] = {}
        for gid, gnode in sorted(groups.items()):
            if gid == 0:
                continue
            gserver = grpc.aio.server()
            rpc.add_RaftServiceServicer_to_server(
                RaftServicer(gnode.node, gnode.addresses,
                             kv=gnode.state.data["kv"]),
                gserver,
            )
            with self._lock:
                gport = self._group_ports[(gid, nid)]
            gbound = gserver.add_insecure_port(f"127.0.0.1:{gport}")
            if gbound != gport:
                raise RuntimeError(
                    f"node {nid} group {gid}: wanted port {gport}, "
                    f"got {gbound}"
                )
            await gserver.start()
            group_servers[gid] = gserver
        await lms_node.start()
        for gid in sorted(group_servers):
            await groups[gid].start()
        campaigns = CampaignRunner(faults, disk_faults, metrics=metrics)
        # Same node-local telemetry timeline the production entrypoint
        # samples, served at GET /admin/timeline per node.
        sampler = TimelineSampler(metrics, interval_s=0.5,
                                  max_points=256).start()
        # The router's drain-aware health poller, like the production
        # entrypoint starts.
        pool.start()
        coordinator = None
        if cfg.lms_groups > 1:
            # Cluster-level coordinator: proposals land on each group's
            # CURRENT leader (in-process — the sim runs every node on
            # this loop), so /admin/reshard works from any node.
            coordinator = ReshardCoordinator(
                ClusterGroupAccess(self),
                course_of=self._wgen.course_of,
                metrics=metrics,
            )
        groups_admin = GroupsAdmin(groups, router=router,
                                   coordinator=coordinator)
        admin, admin_get = make_admin(lms_node, faults, disk_faults,
                                      campaigns,
                                      timeline=sampler.timeline,
                                      pool=pool, groups_admin=groups_admin)
        health = HealthServer(
            metrics,
            health=make_health(nid, lms_node, pool, faults),
            admin=admin, admin_get=admin_get,
            port=self._health_ports[nid],
        )
        await health.start()
        # Same serving-loop heartbeat the production entrypoint runs, so
        # the sim's SLO scrape sees serving_tick_lag/-_stalls per node.
        watchdog = asyncio.get_running_loop().create_task(
            make_serving_watchdog(metrics).run()
        )
        with self._lock:
            self._nodes[nid] = {
                "lms_node": lms_node, "server": server, "health": health,
                "faults": faults, "disk_faults": disk_faults,
                "campaigns": campaigns, "metrics": metrics,
                "pool": pool, "watchdog": watchdog,
                "sampler": sampler,
                "groups": groups, "group_servers": group_servers,
                "router": router,
            }

    async def _stop_node(self, nid: int) -> None:
        with self._lock:
            rec = self._nodes.pop(nid, None)
        if rec is None:
            return
        rec["campaigns"].cancel()
        rec["watchdog"].cancel()
        rec["sampler"].stop()
        await rec["pool"].close()
        await rec["health"].stop()
        if rec.get("router") is not None:
            await rec["router"].close()
        for gid in sorted(rec.get("groups", {}), reverse=True):
            if gid != 0:
                await rec["groups"][gid].stop()
        await rec["lms_node"].stop()
        await rec["server"].stop(None)
        for _gid, gserver in sorted(rec.get("group_servers", {}).items()):
            await gserver.stop(None)


class ClusterGroupAccess:
    """`GroupAccess` over the live cluster: the reshard coordinator's
    proposals chase each group's CURRENT leader replica through
    elections (every sim node shares one loop, so the leader's LMSNode
    is directly reachable in-process — the same way a production
    coordinator would follow NotLeader redirects over the wire)."""

    def __init__(self, cluster: SimCluster) -> None:
        self._cluster = cluster

    def n_groups(self) -> int:
        return self._cluster.group_count()

    def _records(self) -> List[Dict]:
        with self._cluster._lock:
            return list(self._cluster._nodes.values())

    def _leader_node(self, gid: int) -> Optional[LMSNode]:
        for rec in self._records():
            gnode = rec.get("groups", {}).get(gid)
            if (gnode is not None and gnode.node.is_leader
                    and not gnode.recovering):
                return gnode
        return None

    async def _leader(self, gid: int, timeout: float = 15.0) -> LMSNode:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            gnode = self._leader_node(gid)
            if gnode is not None:
                return gnode
            await asyncio.sleep(0.05)
        raise TimeoutError(f"group {gid}: no leader within {timeout}s")

    def users(self) -> List[str]:
        # Auth is replicated to every group (router fan-out), so any
        # replica's user table is a superset view; union to be safe
        # against a lagging follower.
        names: set = set()
        for rec in self._records():
            for gnode in rec.get("groups", {}).values():
                names.update(gnode.state.data["users"].keys())
        return sorted(names)

    def state(self, gid: int):
        gnode = self._leader_node(gid)
        if gnode is None:
            raise RuntimeError(f"group {gid}: no leader replica to read")
        return gnode.state

    def current_map(self) -> RoutingMap:
        gnode = self._leader_node(0)
        if gnode is None:
            for rec in self._records():
                gnode = rec.get("groups", {}).get(0)
                if gnode is not None:
                    break
        raw = (gnode.state.data["kv"].get(ROUTING_MAP_KEY)
               if gnode is not None else None)
        if raw:
            return RoutingMap.from_json(raw)
        return self._cluster._initial_map

    async def read_fence(self, gid: int) -> None:
        gnode = await self._leader(gid)
        await gnode.node.read_barrier()

    async def propose(self, gid: int, op: str, args: Dict) -> None:
        deadline = time.monotonic() + 30.0
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            gnode = await self._leader(gid)
            try:
                await gnode.node.propose(encode_command(op, args))
                return
            except (NotLeader, TimeoutError, asyncio.TimeoutError) as e:
                # Mid-handoff leader churn (the drills induce it on
                # purpose): re-resolve and re-propose. Deterministic
                # request_ids make the replay idempotent.
                last = e
                await asyncio.sleep(0.05)
        raise TimeoutError(f"group {gid}: {op} not committed ({last})")

    async def meta_get(self, key: str) -> Optional[str]:
        gnode = await self._leader(0)
        await gnode.node.read_barrier()
        val = gnode.state.data["kv"].get(key)
        return None if val is None else str(val)

    async def meta_set(self, key: str, value: str) -> None:
        await self.propose(0, "SetVal", {"key": key, "value": value})
