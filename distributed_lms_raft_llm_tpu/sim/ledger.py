"""Client-side acked-write ledger: the Jepsen-style history auditor.

Every write the cluster ACKed (quorum-committed, success on the wire) is
recorded with its ack timestamp and a content fingerprint. Two kinds of
checks consume the history:

- **in-run read-your-writes**: whenever a simulated client performs a
  read, every write acked BEFORE the read began must be visible in the
  response (reads are linearizable by default — a leadership fence runs
  before local state is served — so this is the per-run proof, not an
  assumption). Writes acked concurrently with the read are exempt.
- **end-of-run audit**: after the cluster settles and all faults clear, a
  fresh client re-reads everything; any acked write that cannot be found
  is an acked-write LOSS — the zero-loss SLO the whole fault arsenal is
  supposed to guarantee.

Blob content degrades legally to metadata-only while a replica's copy is
missing (fetch-on-miss budget exhausted), so in-run material reads check
presence always but bytes only when bytes came back; the final audit — no
faults, generous budget — requires the exact bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import metrics_registry as metric

USER = "user"
MATERIAL = "material"
ASSIGNMENT = "assignment"
GRADE = "grade"
QUERY = "query"


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class AckedWrite:
    kind: str
    key: Tuple[str, ...]      # e.g. ("student003", "hw.pdf")
    value: str                # content hash / grade / query text
    acked_at: float           # time.monotonic() when the ack arrived
    group: Optional[int] = None   # owning Raft group at ack time (sharded)


@dataclasses.dataclass(frozen=True)
class ReshardMark:
    """A routing-map flip the workload observed mid-run: every write
    acked before `at` whose `group` == `src` crossed the resharding
    boundary, and the end-of-run audit proving it present on the NEW
    owner is the zero-acked-write-loss evidence for the handoff."""
    course: str
    src: int
    dst: int
    version: int
    at: float


class WriteLedger:
    def __init__(self, metrics=None):
        self.metrics = metrics
        self._writes: List[AckedWrite] = []       # guarded-by: _lock
        self._violations: List[str] = []          # guarded-by: _lock
        self._losses: List[str] = []              # guarded-by: _lock
        self._reshards: List[ReshardMark] = []    # guarded-by: _lock
        self._replica_digests: Optional[Dict] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    # ------------------------------------------------------------- recording

    def record(self, kind: str, key: Tuple[str, ...], value: str = "",
               group: Optional[int] = None) -> None:
        """Call ONLY after the cluster acked the write. `group` tags the
        write with the Raft group that owned its subject at ack time (per
        the routing map the workload routed against), so the audit can
        show which acked writes crossed a later resharding boundary."""
        w = AckedWrite(kind=kind, key=key, value=value,
                       acked_at=time.monotonic(), group=group)
        with self._lock:
            self._writes.append(w)

    def note_reshard(self, course: str, src: int, dst: int,
                     version: int) -> None:
        """Mark a completed routing-map flip (group split/merge)."""
        with self._lock:
            self._reshards.append(ReshardMark(
                course=course, src=src, dst=dst, version=version,
                at=time.monotonic(),
            ))

    def note_replica_digests(self, doc: Optional[Dict]) -> None:
        """Record the settle-time cross-replica digest audit (harness
        `_collect_replica_digests`): per group, every live replica's
        (applied index, state digest). Divergence here is the runtime
        face of state-machine nondeterminism — the replicas_converged
        SLO fails the run on it."""
        with self._lock:
            self._replica_digests = doc

    def acked_before(self, t0: float, kind: str) -> List[AckedWrite]:
        with self._lock:
            return [w for w in self._writes
                    if w.kind == kind and w.acked_at < t0]

    @property
    def acked_count(self) -> int:
        with self._lock:
            return len(self._writes)

    # ------------------------------------------------- in-run read-your-writes

    def _violation(self, msg: str) -> None:
        with self._lock:
            self._violations.append(msg)
        if self.metrics is not None:
            self.metrics.inc(metric.SIM_RYW_VIOLATIONS)

    def check_materials_read(
        self, t0: float, seen: Dict[str, bytes], reader: str
    ) -> None:
        """`seen`: filename -> returned bytes (may be empty: legal
        metadata-only degradation while a blob heals)."""
        for w in self.acked_before(t0, MATERIAL):
            filename = w.key[0]
            if filename not in seen:
                self._violation(
                    f"{reader}: material {filename!r} acked "
                    f"{t0 - w.acked_at:.2f}s before the read but missing"
                )
            elif seen[filename] and content_hash(seen[filename]) != w.value:
                self._violation(
                    f"{reader}: material {filename!r} bytes differ from "
                    "the acked upload"
                )

    def check_grade_read(self, t0: float, response: str, student: str) -> None:
        acked = self.acked_before(t0, GRADE)
        mine = [w for w in acked if w.key[0] == student]
        if mine and "no grade" in response.lower():
            self._violation(
                f"{student}: grade acked before the read but the read "
                f"says {response!r}"
            )

    def check_responses_read(self, t0: float, texts: List[str],
                             student: str) -> None:
        """Answered-or-queued visibility is audited at the END (a query
        may legitimately sit unanswered mid-run); in-run we only require
        that responses the student saw once never disappear — covered by
        the final audit against the full history, so this records
        nothing today and exists as the read hook for future checks."""

    # -------------------------------------------------------- end-of-run audit

    def _loss(self, msg: str) -> None:
        with self._lock:
            self._losses.append(msg)
        if self.metrics is not None:
            self.metrics.inc(metric.SIM_ACKED_WRITE_LOSSES)

    def audit(self, *, users: Dict[str, str], materials: Dict[str, bytes],
              assignments: Dict[str, List[str]],
              grades: Dict[str, str], queries: List[Tuple[str, str]]) -> None:
        """Compare the final cluster state (read through a fresh client
        with no faults active) against every acked write.

        `users`: username -> role for accounts that could log in;
        `materials`: filename -> bytes; `assignments`: student ->
        filenames; `grades`: student -> displayed grade; `queries`:
        (student, query) pairs present on the instructor queue or already
        answered."""
        with self._lock:
            writes = list(self._writes)
        acked_grades: Dict[str, List[str]] = {}
        for w in writes:
            if w.kind == USER and w.key[0] not in users:
                self._loss(f"user {w.key[0]!r} acked but cannot log in")
            elif w.kind == MATERIAL:
                data = materials.get(w.key[0])
                if data is None:
                    self._loss(f"material {w.key[0]!r} acked but absent")
                elif content_hash(data) != w.value:
                    self._loss(f"material {w.key[0]!r} bytes differ from "
                               "the acked upload")
            elif w.kind == ASSIGNMENT:
                student, filename = w.key
                if filename not in assignments.get(student, []):
                    self._loss(f"assignment {filename!r} of {student} "
                               "acked but absent")
            elif w.kind == GRADE:
                acked_grades.setdefault(w.key[0], []).append(w.value)
            elif w.kind == QUERY:
                if (w.key[0], w.value) not in queries:
                    self._loss(f"query {w.value!r} by {w.key[0]} acked "
                               "but on no queue")
        for student, values in acked_grades.items():
            # Grades overwrite each other and concurrent acks leave the
            # winner ambiguous client-side, so the surviving grade must be
            # SOME acked grade — "No grade assigned" after an ack is loss.
            shown = grades.get(student, "")
            if not any(v in shown for v in values):
                self._loss(f"grades {values} of {student} acked but the "
                           f"cluster shows {shown!r}")

    # ---------------------------------------------------------------- report

    def report(self) -> Dict:
        with self._lock:
            by_group: Dict[str, int] = {}
            for w in self._writes:
                if w.group is not None:
                    label = f"group{w.group}"
                    by_group[label] = by_group.get(label, 0) + 1
            crossed = sum(
                1 for w in self._writes for r in self._reshards
                if w.group == r.src and w.acked_at < r.at
            )
            out = {
                "acked_writes": len(self._writes),
                "ryw_violations": list(self._violations),
                "losses": list(self._losses),
            }
            if by_group or self._reshards:
                out["acked_by_group"] = by_group
                out["reshards"] = [
                    {"course": r.course, "src": r.src, "dst": r.dst,
                     "version": r.version}
                    for r in self._reshards
                ]
                # Writes whose owning group changed under them: the
                # population the final audit certifies as lossless
                # across the handoff.
                out["acked_across_reshard"] = crossed
            if self._replica_digests is not None:
                out["replica_digests"] = self._replica_digests
            return out
