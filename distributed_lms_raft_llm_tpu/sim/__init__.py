"""Semester simulator: one continuously-verified production scenario.

The robustness PRs built every primitive the deployment story needs —
chaos over real gRPC, disk-fault injection, crash-consistent storage with
rejoin-by-InstallSnapshot, breakers + degraded fallback, TimeoutNow
leadership transfer, runtime membership changes — but only as separate
tests. This package composes them into ONE Jepsen-style scenario:

- `workload`  — seeded deterministic trace of simulated students across
  courses following a diurnal load curve (the full op mix, including on-
  and off-topic `ask_llm`);
- `events`    — a seeded operations schedule injected mid-run (rolling
  restart via TimeoutNow transfer, a storage-recovery quarantine via the
  disk-fault admin plane, a membership add/remove, chaos campaigns via
  `POST /admin/faults`);
- `ledger`    — a client-side acked-write ledger proving zero acked-write
  loss and read-your-writes across the whole run;
- `slo`       — continuous fast/slow burn-rate SLO evaluation DURING the
  run (alerts classified against the fault schedule) plus the end-of-run
  assertions from `/metrics` + `/healthz`;
- `cluster`   — the in-process cluster under test (real gRPC, real admin
  plane, restartable nodes);
- `harness`   — `SemesterSim`, wiring it all together and emitting one
  BENCH-schema record (`scripts/semester_sim.py`).

Everything that decides WHAT happens (op trace, event schedule) is a pure
function of the seed, so a failed run replays from its seed; only the
interleaving with real sockets is nondeterministic.
"""

from ..config import SimConfig
from .cluster import SimCluster
from .events import SimEvent, plan_events
from .harness import SemesterSim
from .ledger import WriteLedger
from .slo import ContinuousSloEngine, SloReport, evaluate_slos
from .workload import SimOp, WorkloadGenerator, trace_digest

__all__ = [
    "SimConfig",
    "SimCluster",
    "SimEvent",
    "plan_events",
    "SemesterSim",
    "WriteLedger",
    "ContinuousSloEngine",
    "SloReport",
    "evaluate_slos",
    "SimOp",
    "WorkloadGenerator",
    "trace_digest",
]
