"""End-of-run SLO assertions from `/metrics` and `/healthz`.

The semester sim's verdict: after the workload finishes, faults clear,
and the cluster settles, the SLOs are evaluated against what the CLUSTER
exports (every node's `/metrics` and `/healthz` snapshots, scraped over
HTTP) plus the harness's own client-side series — not against internal
test handles — so the same checks an operator's alerting would run are
what gate the run.

Checks:
- zero acked-write loss + read-your-writes (the ledger's history audit);
- answer p95 under the bound, both client-observed (`sim_ask_latency`)
  and server-side (every node's `llm_ttft` p95 from `/metrics`);
- degraded-answer rate bounded (Σ tutoring_degraded / Σ llm_requests);
- every tutoring breaker re-closed (`/healthz`);
- no node stuck `storage_recovering` (`/healthz` + the gauge);
- `raft_tick_stalls` bounded across the cluster;
- every planned operations event completed (`event_failures` from the
  scheduler): the acceptance criteria — >=1 transfer, >=1 quarantine,
  >=1 membership change — are part of the verdict, not just the CLI's
  exit code.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from ..config import SimConfig
from ..utils import metrics_registry as metric


@dataclasses.dataclass(frozen=True)
class SloCheck:
    name: str
    ok: bool
    observed: str
    bound: str


@dataclasses.dataclass
class SloReport:
    checks: List[SloCheck]

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def failures(self) -> List[SloCheck]:
        return [c for c in self.checks if not c.ok]

    def to_dict(self) -> Dict:
        return {
            "ok": self.ok,
            "checks": {c.name: {"ok": c.ok, "observed": c.observed,
                                "bound": c.bound}
                       for c in self.checks},
        }


def _counter(snap: Dict, name: str) -> int:
    return int(snap.get("counters", {}).get(name, 0))


def _gauge(snap: Dict, name: str, default: float = 0.0) -> float:
    return float(snap.get("gauges", {}).get(name, default))


def evaluate_slos(
    cfg: SimConfig,
    node_metrics: Dict[int, Dict],
    node_health: Dict[int, Dict],
    sim_metrics: Dict,
    ledger_report: Dict,
    *,
    event_failures: Sequence[Dict] = (),
    metrics=None,
) -> SloReport:
    """`node_metrics`/`node_health`: node id -> scraped JSON snapshots of
    every node alive at the end of the run; `sim_metrics`: the harness's
    own Metrics snapshot; `ledger_report`: `WriteLedger.report()`;
    `event_failures`: the scheduler's `ok=False` outcomes."""
    checks: List[SloCheck] = []

    def check(name: str, ok: bool, observed: str, bound: str) -> None:
        checks.append(SloCheck(name=name, ok=ok, observed=observed,
                               bound=bound))
        if not ok and metrics is not None:
            metrics.inc(metric.SIM_SLO_VIOLATIONS)

    losses = ledger_report["losses"]
    check("zero_acked_write_loss", not losses,
          f"{len(losses)} lost of {ledger_report['acked_writes']} acked"
          + (f": {losses[:3]}" if losses else ""), "0 lost")
    ryw = ledger_report["ryw_violations"]
    check("read_your_writes", not ryw,
          f"{len(ryw)} violations" + (f": {ryw[:3]}" if ryw else ""), "0")

    ask = sim_metrics.get("latency", {}).get("sim_ask_latency", {})
    client_p95 = ask.get("p95_s")
    check(
        "answer_p95_client", client_p95 is None
        or client_p95 <= cfg.slo_answer_p95_s,
        f"{client_p95 if client_p95 is not None else 'n/a'} s "
        f"({ask.get('count', 0)} asks)",
        f"<= {cfg.slo_answer_p95_s} s",
    )
    worst = 0.0
    for snap in node_metrics.values():
        hist = snap.get("latency", {}).get("llm_ttft", {})
        worst = max(worst, float(hist.get("p95_s", 0.0)))
    check("answer_p95_nodes", worst <= cfg.slo_answer_p95_s,
          f"worst node llm_ttft p95 {worst:.3f} s",
          f"<= {cfg.slo_answer_p95_s} s")

    degraded = sum(_counter(s, "tutoring_degraded")
                   for s in node_metrics.values())
    requests = sum(_counter(s, "llm_requests") for s in node_metrics.values())
    rate = degraded / requests if requests else 0.0
    check("degraded_rate", rate <= cfg.slo_degraded_rate_max,
          f"{degraded}/{requests} = {rate:.3f}",
          f"<= {cfg.slo_degraded_rate_max}")

    open_breakers = {
        nid: h.get("tutoring_breaker", {}).get("state")
        for nid, h in node_health.items()
        if h.get("tutoring_breaker", {}).get("state") != "closed"
    }
    check("breakers_closed", not open_breakers,
          f"open: {open_breakers}" if open_breakers else "all closed",
          "closed on every node")

    stuck = sorted(
        set(
            [nid for nid, h in node_health.items()
             if h.get("storage_recovering")]
            + [nid for nid, s in node_metrics.items()
               if _gauge(s, "storage_recovering") > 0]
        )
    )
    check("no_stuck_storage_recovery", not stuck,
          f"recovering: {stuck}" if stuck else "none recovering", "none")

    stalls = sum(_counter(s, "raft_tick_stalls")
                 for s in node_metrics.values())
    check("tick_stalls", stalls <= cfg.slo_tick_stalls_max,
          f"{stalls} stalls summed", f"<= {cfg.slo_tick_stalls_max}")

    failed = [f"{o['kind']}: {o['detail']}" for o in event_failures]
    check("events_completed", not failed,
          f"{len(failed)} failed" + (f": {failed[:3]}" if failed else ""),
          "every planned event ok")

    return SloReport(checks=checks)
