"""SLO evaluation: continuous burn-rate windows in-run, verdict at end.

Two layers, one set of bounds (`SimConfig.slo_*`):

**Continuous (`ContinuousSloEngine`)** — the semester sim no longer
waits for the post-mortem: while the workload runs, a telemetry loop
polls every node's `/metrics` into a merged cluster timeline
(utils/scrape.py) and evaluates each SLO over TWO sliding windows — a
short *fast* window that pages quickly and a long *slow* window that
demands sustained evidence — the SRE-workbook multi-window burn-rate
pattern scaled to sim time. Burn = (budget consumption rate) / (budget
accrual rate): a degraded-answer rate of 2x its bound burns at 2.0. An
alert needs `sustain` consecutive over-threshold evaluations to raise
(one noisy sample never pages) and the same streak below to clear;
raises and clears are recorded as timeline events, counted in
`sim_burn_alerts`, and carried — classified against the operations
schedule's fault phases — into the verdict and the BENCH record. On the
healthy baseline the engine must stay silent (`no_false_alarms`); during
an injected fault it must fire (the tier-1 sim pins both).

**End-of-run (`evaluate_slos`)** — unchanged in spirit: after faults
clear and the cluster settles, the checks run against what the CLUSTER
exports (every node's `/metrics`/`/healthz` over HTTP) plus the
harness's client-side series, so the same checks an operator's alerting
would run are what gate the run. Metric names route through
`utils/metrics_registry` constants and the shared snapshot readers
(utils/timeline.snap_*) — the metrics-registry lint rule checks these
READ sites too, so an SLO bound on a never-declared series fails lint
instead of silently reading 0.

The verdict also carries **per-stage p95 breakdowns** computed from the
flight recorder's retained traces (utils/tracing.py): the aggregate
`answer_p95` bound says *whether* the cluster met its budget, the stage
breakdown says *where* the budget went. Stage quantiles use the shared
nearest-rank helper (utils/metrics.percentile_of_sorted), the same
formula every histogram and timeline percentile in the repo uses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..config import SimConfig
from ..utils import metrics_registry as metric
from ..utils.metrics import Metrics, percentile_of_sorted
from ..utils.timeline import (
    Timeline,
    degraded_rate_burn,
    snap_counter,
    snap_gauge,
    snap_hist,
)


@dataclasses.dataclass(frozen=True)
class SloCheck:
    name: str
    ok: bool
    observed: str
    bound: str


@dataclasses.dataclass
class SloReport:
    checks: List[SloCheck]
    # Span name -> {count, p50_s, p95_s, max_s}: where the answer budget
    # actually went, computed from retained traces (stage_breakdown).
    stage_p95s: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    # Measured shared-prefix KV cache hit rate on the tutoring node
    # (prefix_cache_hit_rate gauge); None when the serving engine runs
    # without the cache (echo stand-in, bucketed engine). Informational
    # — carried in the verdict and the BENCH record, not a pass/fail
    # bound.
    prefix_cache_hit_rate: Any = None
    # The continuous engine's report (windows, evaluations, alerts with
    # fault classification); None when the run evaluated SLOs only at
    # the end ([sim] continuous_slos = false).
    continuous: Optional[Dict[str, Any]] = None
    # Tutoring-fleet summary (router spill/hedge counters + per-node
    # end-state map); None for a one-node fleet.
    fleet: Optional[Dict[str, Any]] = None
    # Background scoring-tenant summary (jobs/quanta/tokens from the
    # tutoring fleet's counters); None when the tenant is disabled.
    scoring: Optional[Dict[str, Any]] = None
    # Sharded-control-plane summary (routing map, per-group leaders,
    # reshard evidence); None for a single-group deployment.
    groups: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def failures(self) -> List[SloCheck]:
        return [c for c in self.checks if not c.ok]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "checks": {c.name: {"ok": c.ok, "observed": c.observed,
                                "bound": c.bound}
                       for c in self.checks},
            "stage_p95s": self.stage_p95s,
            "prefix_cache_hit_rate": self.prefix_cache_hit_rate,
            "continuous": self.continuous,
            "fleet": self.fleet,
            "scoring": self.scoring,
            "groups": self.groups,
        }


def _walk_spans(span: Dict[str, Any], out: Dict[str, List[float]]) -> None:
    out.setdefault(span["name"], []).append(float(span.get("duration_s",
                                                           0.0)))
    for child in span.get("children", ()):
        _walk_spans(child, out)


def stage_breakdown(
    traces: Sequence[Dict[str, Any]],
) -> Dict[str, Dict[str, float]]:
    """Per-stage latency stats from assembled trace dicts
    (`Tracer.records()` / `GET /admin/trace/<id>` shape): span name ->
    {count, p50_s, p95_s, max_s}. Spans aggregate by NAME — `queue.wait`
    collects every request's queue wait regardless of which node recorded
    it — so the result reads as attributable per-stage budgets next to
    the aggregate `answer_p95` SLO bound."""
    by_name: Dict[str, List[float]] = {}
    for trace in traces:
        for root in trace.get("spans", ()):
            _walk_spans(root, by_name)
    out: Dict[str, Dict[str, float]] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "p50_s": round(percentile_of_sorted(durs, 50), 6),
            "p95_s": round(percentile_of_sorted(durs, 95), 6),
            "max_s": round(durs[-1], 6),
        }
    return out


# ===================================================== continuous engine


FAST = "fast"
SLOW = "slow"

# The continuously evaluated SLOs (each over both windows).
CONTINUOUS_SLOS = ("answer_p95", "degraded_rate", "tick_stalls")


@dataclasses.dataclass
class BurnAlert:
    """One raised burn-rate alert and its lifecycle."""

    slo: str
    window: str                       # FAST | SLOW
    window_s: float
    raised_at_s: float                # offset from workload start
    peak_burn: float
    cleared_at_s: Optional[float] = None
    # Set by finish(): whether the raise falls inside (a margin around)
    # an injected-fault phase. An alert outside every fault phase is a
    # false alarm and fails the verdict's `no_false_alarms` check.
    during_fault: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "slo": self.slo,
            "window": self.window,
            "window_s": round(self.window_s, 3),
            "raised_at_s": round(self.raised_at_s, 3),
            "cleared_at_s": (round(self.cleared_at_s, 3)
                             if self.cleared_at_s is not None else None),
            "peak_burn": round(self.peak_burn, 3),
            "during_fault": self.during_fault,
        }


class ContinuousSloEngine:
    """Fast/slow multi-window burn-rate evaluation over a live run.

    `cluster` is the scrape aggregator's merged timeline (node-side
    counters: degraded rate, tick stalls); `sim_metrics` is the
    harness's own client-side Metrics (the answer-latency SLO uses its
    TRUE sliding-window percentile — a cumulative reservoir would hold
    an early spike against the whole run). Windows default to fractions
    of the run so the same config scales from the 16 s tier-1 sim to an
    hours-long soak; production windows come from [telemetry].
    """

    def __init__(
        self,
        cfg: SimConfig,
        cluster: Timeline,
        sim_metrics: Metrics,
        *,
        fast_window_s: Optional[float] = None,
        slow_window_s: Optional[float] = None,
        fast_burn: float = 1.2,
        slow_burn: float = 1.0,
        sustain: int = 2,
        metrics: Optional[Metrics] = None,
    ):
        self.cfg = cfg
        self.cluster = cluster
        self.sim_metrics = sim_metrics
        self.metrics = metrics
        self.windows: Dict[str, float] = {
            FAST: (fast_window_s if fast_window_s is not None
                   else max(1.0, 0.06 * cfg.duration_s)),
            SLOW: (slow_window_s if slow_window_s is not None
                   else max(4.0, 0.30 * cfg.duration_s)),
        }
        self.burn_thresholds: Dict[str, float] = {
            FAST: fast_burn, SLOW: slow_burn,
        }
        self.sustain = max(1, sustain)
        self.alerts: List[BurnAlert] = []
        self.evaluations = 0
        self.windows_evaluated: Dict[str, int] = {
            slo: 0 for slo in CONTINUOUS_SLOS
        }
        self._over: Dict[Tuple[str, str], int] = {}
        self._under: Dict[Tuple[str, str], int] = {}
        self._active: Dict[Tuple[str, str], BurnAlert] = {}

    # -------------------------------------------------------- burn math

    def _burn(self, slo: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Budget consumption rate over the window as a multiple of the
        budget's accrual rate; None = the window holds no evidence (no
        samples / no traffic to judge), which never moves a streak."""
        cfg = self.cfg
        if slo == "answer_p95":
            p95 = self.sim_metrics.hist(
                metric.SIM_ASK_LATENCY
            ).window_percentile(window_s, 95)
            if p95 is None:
                return None
            return p95 / cfg.slo_answer_p95_s
        if slo == "degraded_rate":
            return degraded_rate_burn(self.cluster, window_s,
                                      cfg.slo_degraded_rate_max, now)
        if slo == "tick_stalls":
            rate = self.cluster.counter_rate(metric.RAFT_TICK_STALLS,
                                             window_s, now)
            if rate is None:
                return None
            budget_rate = cfg.slo_tick_stalls_max / cfg.duration_s
            return rate / budget_rate if budget_rate > 0 else 0.0
        raise ValueError(f"unknown continuous SLO {slo!r}")

    # ------------------------------------------------------- evaluation

    def evaluate(self, at_s: float, now: Optional[float] = None) -> None:
        """One evaluation round at offset `at_s` from workload start;
        `now` overrides the timeline queries' wall clock (tests feed
        synthetic timelines on a synthetic clock)."""
        self.evaluations += 1
        for slo in CONTINUOUS_SLOS:
            for wname, window_s in self.windows.items():
                burn = self._burn(slo, window_s, now)
                if burn is None:
                    continue
                self.windows_evaluated[slo] += 1
                self._update(slo, wname, window_s, burn, at_s)

    def _update(self, slo: str, wname: str, window_s: float,
                burn: float, at_s: float) -> None:
        key = (slo, wname)
        threshold = self.burn_thresholds[wname]
        active = self._active.get(key)
        if burn >= threshold:
            self._under[key] = 0
            self._over[key] = self._over.get(key, 0) + 1
            if active is not None:
                active.peak_burn = max(active.peak_burn, burn)
            elif self._over[key] >= self.sustain:
                alert = BurnAlert(slo=slo, window=wname,
                                  window_s=window_s,
                                  raised_at_s=at_s, peak_burn=burn)
                self._active[key] = alert
                self.alerts.append(alert)
                self.cluster.record_event(
                    "slo_alert_raised",
                    f"{slo} burn {burn:.2f} over {window_s:.1f}s "
                    f"({wname} window, threshold {threshold})",
                    at_s=round(at_s, 3), slo=slo, window=wname,
                )
                if self.metrics is not None:
                    self.metrics.inc(metric.SIM_BURN_ALERTS)
        else:
            self._over[key] = 0
            if active is not None:
                self._under[key] = self._under.get(key, 0) + 1
                if self._under[key] >= self.sustain:
                    active.cleared_at_s = at_s
                    del self._active[key]
                    self._under[key] = 0
                    self.cluster.record_event(
                        "slo_alert_cleared",
                        f"{slo} burn {burn:.2f} back under {threshold} "
                        f"({wname} window)",
                        at_s=round(at_s, 3), slo=slo, window=wname,
                    )

    # ----------------------------------------------------------- verdict

    def finish(self, fault_windows: Sequence[Tuple[float, float]],
               margin_before_s: float = 1.0,
               margin_after_s: Optional[float] = None) -> None:
        """Classify every alert against the injected-fault phases: an
        alert raised inside [start - margin_before, end + margin_after]
        of some fault phase is EXPECTED; anything else is a false alarm.
        The after-margin defaults to the slow window plus slack — a burn
        window legitimately keeps paging until the fault has slid out of
        it."""
        after = (margin_after_s if margin_after_s is not None
                 else self.windows[SLOW] + 2.0)
        for alert in self.alerts:
            alert.during_fault = any(
                t0 - margin_before_s <= alert.raised_at_s <= t1 + after
                for t0, t1 in fault_windows
            )

    def false_alarms(self) -> List[BurnAlert]:
        return [a for a in self.alerts if not a.during_fault]

    def report(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "windows_s": {k: round(v, 3) for k, v in self.windows.items()},
            "burn_thresholds": dict(self.burn_thresholds),
            "sustain": self.sustain,
            "evaluations": self.evaluations,
            "windows_evaluated": dict(self.windows_evaluated),
            "alerts": [a.to_dict() for a in self.alerts],
        }


# ===================================================== end-of-run checks


def evaluate_slos(
    cfg: SimConfig,
    node_metrics: Dict[int, Dict[str, Any]],
    node_health: Dict[int, Dict[str, Any]],
    sim_metrics: Dict[str, Any],
    ledger_report: Dict[str, Any],
    *,
    event_failures: Sequence[Dict[str, Any]] = (),
    traces: Sequence[Dict[str, Any]] = (),
    tutoring_metrics: Optional[Dict[str, Any]] = None,
    metrics: Optional[Metrics] = None,
    continuous: Optional[Dict[str, Any]] = None,
    fleet: Optional[Dict[str, Any]] = None,
    scoring: Optional[Dict[str, Any]] = None,
    groups: Optional[Dict[str, Any]] = None,
) -> SloReport:
    """`node_metrics`/`node_health`: node id -> scraped JSON snapshots of
    every node alive at the end of the run; `sim_metrics`: the harness's
    own Metrics snapshot; `ledger_report`: `WriteLedger.report()`;
    `event_failures`: the scheduler's `ok=False` outcomes; `traces`: the
    flight recorder's retained trace trees (per-stage breakdowns);
    `tutoring_metrics`: the tutoring node's serving-queue snapshot (the
    verdict carries its measured prefix_cache_hit_rate); `continuous`:
    the ContinuousSloEngine's report — when present, the in-run alert
    discipline becomes part of the verdict (windows really evaluated,
    zero false alarms)."""
    checks: List[SloCheck] = []

    def check(name: str, ok: bool, observed: str, bound: str) -> None:
        checks.append(SloCheck(name=name, ok=ok, observed=observed,
                               bound=bound))
        if not ok and metrics is not None:
            metrics.inc(metric.SIM_SLO_VIOLATIONS)

    losses = ledger_report["losses"]
    check("zero_acked_write_loss", not losses,
          f"{len(losses)} lost of {ledger_report['acked_writes']} acked"
          + (f": {losses[:3]}" if losses else ""), "0 lost")
    ryw = ledger_report["ryw_violations"]
    check("read_your_writes", not ryw,
          f"{len(ryw)} violations" + (f": {ryw[:3]}" if ryw else ""), "0")

    ask = snap_hist(sim_metrics, metric.SIM_ASK_LATENCY)
    client_p95 = ask.get("p95_s")
    check(
        "answer_p95_client", client_p95 is None
        or client_p95 <= cfg.slo_answer_p95_s,
        f"{client_p95 if client_p95 is not None else 'n/a'} s "
        f"({ask.get('count', 0)} asks)",
        f"<= {cfg.slo_answer_p95_s} s",
    )
    worst = 0.0
    for snap in node_metrics.values():
        hist = snap_hist(snap, metric.LLM_TTFT)
        worst = max(worst, float(hist.get("p95_s", 0.0)))
    check("answer_p95_nodes", worst <= cfg.slo_answer_p95_s,
          f"worst node llm_ttft p95 {worst:.3f} s",
          f"<= {cfg.slo_answer_p95_s} s")

    degraded = sum(snap_counter(s, metric.TUTORING_DEGRADED)
                   for s in node_metrics.values())
    requests = sum(snap_counter(s, metric.LLM_REQUESTS)
                   for s in node_metrics.values())
    rate = degraded / requests if requests else 0.0
    check("degraded_rate", rate <= cfg.slo_degraded_rate_max,
          f"{degraded}/{requests} = {rate:.3f}",
          f"<= {cfg.slo_degraded_rate_max}")

    open_breakers = {
        nid: h.get("tutoring_breaker", {}).get("state")
        for nid, h in node_health.items()
        if h.get("tutoring_breaker", {}).get("state") != "closed"
    }
    check("breakers_closed", not open_breakers,
          f"open: {open_breakers}" if open_breakers else "all closed",
          "closed on every node")

    stuck = sorted(
        set(
            [nid for nid, h in node_health.items()
             if h.get("storage_recovering")]
            + [nid for nid, s in node_metrics.items()
               if snap_gauge(s, metric.STORAGE_RECOVERING) > 0]
        )
    )
    check("no_stuck_storage_recovery", not stuck,
          f"recovering: {stuck}" if stuck else "none recovering", "none")

    stalls = sum(snap_counter(s, metric.RAFT_TICK_STALLS)
                 for s in node_metrics.values())
    check("tick_stalls", stalls <= cfg.slo_tick_stalls_max,
          f"{stalls} stalls summed", f"<= {cfg.slo_tick_stalls_max}")

    failed = [f"{o['kind']}: {o['detail']}" for o in event_failures]
    check("events_completed", not failed,
          f"{len(failed)} failed" + (f": {failed[:3]}" if failed else ""),
          "every planned event ok")

    # Resumable-stream / conversational-session verdicts. The digest
    # check is unconditional: the client verifies every completed stream
    # against the final chunk's answer digest, so ANY duplicated or
    # dropped token — including across a mid-stream failover — lands in
    # this counter (0 streams trivially passes).
    mismatches = snap_counter(sim_metrics, metric.SIM_STREAM_DIGEST_MISMATCH)
    streamed_turns = snap_counter(sim_metrics, metric.SIM_SESSION_TURNS)
    check("stream_digest_parity", mismatches == 0,
          f"{mismatches} digest mismatches over {streamed_turns} "
          "streamed turns",
          "0 — streams monotone, gap-free, duplicate-free")
    if round(cfg.session_fraction * cfg.students) >= 1:
        turns_failed = snap_counter(sim_metrics,
                                    metric.SIM_SESSION_TURNS_FAILED)
        check("session_turns_completed", streamed_turns >= 1,
              f"{streamed_turns} ok / {turns_failed} failed",
              ">= 1 streamed session turn completed")
        tt = snap_hist(sim_metrics, metric.SIM_TURN_TTFT)
        ttft_p95 = tt.get("p95_s")
        check(
            "turn_ttft_p95",
            ttft_p95 is None or ttft_p95 <= cfg.slo_turn_ttft_p95_s,
            f"{ttft_p95 if ttft_p95 is not None else 'n/a'} s "
            f"({tt.get('count', 0)} turns)",
            f"<= {cfg.slo_turn_ttft_p95_s} s",
        )
        if cfg.tutoring_engine == "tiny-paged" and streamed_turns >= 2:
            # Follow-up turns must actually splice the session prefix:
            # turn N+1 starts from turn N's published transcript blocks,
            # so the radix cache records hit tokens (> 0) for the chain.
            hit_tokens = snap_counter(tutoring_metrics or {},
                                      metric.PREFIX_CACHE_HIT_TOKENS)
            check("session_prefix_hits", hit_tokens > 0,
                  f"{hit_tokens} prefix-cache hit tokens",
                  "> 0 hit tokens across follow-up turns")

    if continuous is not None:
        evaluated = continuous.get("windows_evaluated", {})
        missing = [slo for slo in CONTINUOUS_SLOS
                   if not evaluated.get(slo)]
        check(
            "burn_windows_evaluated", not missing,
            f"evaluations per SLO: {evaluated}"
            + (f"; never evaluated: {missing}" if missing else ""),
            ">= 1 burn-rate window evaluated per SLO",
        )
        false_alarms = [a for a in continuous.get("alerts", [])
                        if not a.get("during_fault")]
        check(
            "no_false_alarms", not false_alarms,
            (f"{len(false_alarms)} alert(s) outside every fault phase: "
             f"{false_alarms[:3]}") if false_alarms
            else f"{len(continuous.get('alerts', []))} alert(s), all "
                 "inside fault phases",
            "every alert inside an injected-fault phase",
        )

    if fleet is not None:
        # Fleet verdicts (only when there IS a fleet, [sim]
        # tutoring_nodes > 1): the drills must leave measured evidence
        # — >=1 router spill and >=1 hedge win — and no node may end the
        # run stuck out of the ring (ejected/draining after settle means
        # a drain that never rejoined).
        if fleet.get("drills"):
            check("fleet_spill_observed", fleet.get("spills", 0) >= 1,
                  f"{fleet.get('spills', 0)} spills", ">= 1 router spill")
            check("fleet_hedge_win_observed",
                  fleet.get("hedge_wins", 0) >= 1,
                  f"{fleet.get('hedge_wins', 0)} hedge wins "
                  f"({fleet.get('hedges', 0)} hedged)",
                  ">= 1 hedged answer won")
            check("stream_resume_observed",
                  fleet.get("stream_resumes", 0) >= 1,
                  f"{fleet.get('stream_resumes', 0)} resumes "
                  f"({fleet.get('stream_stalls', 0)} stall trips)",
                  ">= 1 mid-stream failover resumed at its offset")
        stuck_nodes = [n["address"] for n in fleet.get("nodes", ())
                       if n.get("state") in ("draining", "ejected")]
        check("fleet_nodes_routable", not stuck_nodes,
              f"out of ring: {stuck_nodes}" if stuck_nodes
              else f"all {fleet.get('size', 0)} nodes routable",
              "no node left ejected/draining")

    if scoring is not None and scoring.get("expected"):
        # The bulk-grading night's completion claim: the background
        # tenant finished its job(s) in the idle lanes (the "p95
        # unchanged" half is enforced by no_false_alarms above — the
        # grading window is NOT a fault window, so a scoring-induced
        # burn alert fails the run).
        done = int(scoring.get("jobs_completed", 0))
        failed = int(scoring.get("jobs_failed", 0))
        check(
            "bulk_scoring_completed", done >= 1 and failed == 0,
            f"{done} completed / {failed} failed "
            f"({scoring.get('quanta', 0)} quanta, "
            f"{scoring.get('scored_tokens', 0)} tokens scored)",
            ">= 1 bulk job completed, 0 failed",
        )

    if groups is not None:
        # Sharded-control-plane verdicts ([sim] lms_groups > 1): every
        # Raft group must end the run with a leader (the per-group
        # leader-loss drill healed), and when a live split was planned
        # the routing map must have flipped — the staged handoff ran to
        # `done`, not just "was attempted". Zero acked-write loss ACROSS
        # the flip is already pinned by zero_acked_write_loss above: the
        # ledger tags every write with its owning group and the audit
        # re-reads the moved keys through the post-flip map.
        leaderless = sorted(
            gid for gid, nid in groups.get("leaders", {}).items()
            if nid is None
        )
        check(
            "groups_routable", not leaderless,
            f"leaderless groups: {leaderless}" if leaderless
            else (f"all {groups.get('n_groups', 0)} groups have leaders: "
                  f"{groups.get('leaders', {})}"),
            "a leader per Raft group",
        )
        digests = groups.get("replica_digests") or {}
        if digests:
            # The runtime face of the state-machine-determinism lint
            # rule: at settle, every replica of every group sat at the
            # same applied index with the same LMSState.digest chain
            # value — including group members restored mid-run via
            # InstallSnapshot during the split drill. A divergent digest
            # means some applier observed clock/RNG/iteration-order
            # nondeterminism the static rule could not see.
            diverged = sorted(
                gid for gid, rows in digests.get("groups", {}).items()
                if len({r.get("digest") for r in rows.values()}) > 1
            )
            check(
                "replicas_converged", bool(digests.get("converged")),
                (f"diverged/undrained groups: {diverged}" if diverged
                 else "digest audit did not converge") if not
                digests.get("converged") else
                ", ".join(
                    f"group {gid}: {len(rows)} replicas @ "
                    f"{next(iter(rows.values())).get('applied')} = "
                    f"{next(iter(rows.values())).get('digest')}"
                    for gid, rows in sorted(
                        digests.get("groups", {}).items()
                    )
                ),
                "identical per-group state digests at settle",
            )
        if groups.get("expected_reshard"):
            reshards = groups.get("reshards", [])
            version = int(
                groups.get("routing_map", {}).get("version", 1)
            )
            check(
                "reshard_completed", bool(reshards) and version > 1,
                f"{len(reshards)} reshard(s), map version {version}"
                + (f", {groups.get('acked_across_reshard', 0)} acked "
                   "writes crossed the boundary" if reshards else ""),
                ">= 1 completed handoff, routing map flipped",
            )

    hit_rate = snap_gauge(tutoring_metrics or {},
                          metric.PREFIX_CACHE_HIT_RATE, default=-1.0)
    return SloReport(
        checks=checks, stage_p95s=stage_breakdown(traces),
        prefix_cache_hit_rate=hit_rate if hit_rate >= 0 else None,
        continuous=continuous,
        fleet=fleet,
        scoring=scoring,
        groups=groups,
    )
