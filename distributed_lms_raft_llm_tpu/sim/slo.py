"""End-of-run SLO assertions from `/metrics` and `/healthz`.

The semester sim's verdict: after the workload finishes, faults clear,
and the cluster settles, the SLOs are evaluated against what the CLUSTER
exports (every node's `/metrics` and `/healthz` snapshots, scraped over
HTTP) plus the harness's own client-side series — not against internal
test handles — so the same checks an operator's alerting would run are
what gate the run.

Checks:
- zero acked-write loss + read-your-writes (the ledger's history audit);
- answer p95 under the bound, both client-observed (`sim_ask_latency`)
  and server-side (every node's `llm_ttft` p95 from `/metrics`);
- degraded-answer rate bounded (Σ tutoring_degraded / Σ llm_requests);
- every tutoring breaker re-closed (`/healthz`);
- no node stuck `storage_recovering` (`/healthz` + the gauge);
- `raft_tick_stalls` bounded across the cluster;
- every planned operations event completed (`event_failures` from the
  scheduler): the acceptance criteria — >=1 transfer, >=1 quarantine,
  >=1 membership change — are part of the verdict, not just the CLI's
  exit code.

The verdict also carries **per-stage p95 breakdowns** computed from the
flight recorder's retained traces (utils/tracing.py): the aggregate
`answer_p95` bound says *whether* the cluster met its budget, the stage
breakdown says *where* the budget went (raft commit vs gate vs queue
wait vs engine programs) — so an SLO failure arrives self-explaining
instead of starting the next perf investigation from guesswork.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence

from ..config import SimConfig
from ..utils import metrics_registry as metric


@dataclasses.dataclass(frozen=True)
class SloCheck:
    name: str
    ok: bool
    observed: str
    bound: str


@dataclasses.dataclass
class SloReport:
    checks: List[SloCheck]
    # Span name -> {count, p50_s, p95_s, max_s}: where the answer budget
    # actually went, computed from retained traces (stage_breakdown).
    stage_p95s: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict
    )
    # Measured shared-prefix KV cache hit rate on the tutoring node
    # (prefix_cache_hit_rate gauge); None when the serving engine runs
    # without the cache (echo stand-in, bucketed engine). Informational
    # — carried in the verdict and the BENCH record, not a pass/fail
    # bound.
    prefix_cache_hit_rate: Any = None

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def failures(self) -> List[SloCheck]:
        return [c for c in self.checks if not c.ok]

    def to_dict(self) -> Dict:
        return {
            "ok": self.ok,
            "checks": {c.name: {"ok": c.ok, "observed": c.observed,
                                "bound": c.bound}
                       for c in self.checks},
            "stage_p95s": self.stage_p95s,
            "prefix_cache_hit_rate": self.prefix_cache_hit_rate,
        }


def _walk_spans(span: Dict[str, Any], out: Dict[str, List[float]]) -> None:
    out.setdefault(span["name"], []).append(float(span.get("duration_s",
                                                           0.0)))
    for child in span.get("children", ()):
        _walk_spans(child, out)


def stage_breakdown(
    traces: Sequence[Dict[str, Any]],
) -> Dict[str, Dict[str, float]]:
    """Per-stage latency stats from assembled trace dicts
    (`Tracer.records()` / `GET /admin/trace/<id>` shape): span name ->
    {count, p50_s, p95_s, max_s}. Spans aggregate by NAME — `queue.wait`
    collects every request's queue wait regardless of which node recorded
    it — so the result reads as attributable per-stage budgets next to
    the aggregate `answer_p95` SLO bound."""
    by_name: Dict[str, List[float]] = {}
    for trace in traces:
        for root in trace.get("spans", ()):
            _walk_spans(root, by_name)
    out: Dict[str, Dict[str, float]] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        n = len(durs)
        out[name] = {
            "count": n,
            "p50_s": round(durs[n // 2], 6),
            "p95_s": round(durs[min(int(n * 0.95), n - 1)], 6),
            "max_s": round(durs[-1], 6),
        }
    return out


def _counter(snap: Dict, name: str) -> int:
    return int(snap.get("counters", {}).get(name, 0))


def _gauge(snap: Dict, name: str, default: float = 0.0) -> float:
    return float(snap.get("gauges", {}).get(name, default))


def evaluate_slos(
    cfg: SimConfig,
    node_metrics: Dict[int, Dict],
    node_health: Dict[int, Dict],
    sim_metrics: Dict,
    ledger_report: Dict,
    *,
    event_failures: Sequence[Dict] = (),
    traces: Sequence[Dict[str, Any]] = (),
    tutoring_metrics: Dict = None,
    metrics=None,
) -> SloReport:
    """`node_metrics`/`node_health`: node id -> scraped JSON snapshots of
    every node alive at the end of the run; `sim_metrics`: the harness's
    own Metrics snapshot; `ledger_report`: `WriteLedger.report()`;
    `event_failures`: the scheduler's `ok=False` outcomes; `traces`: the
    flight recorder's retained trace trees (per-stage breakdowns);
    `tutoring_metrics`: the tutoring node's serving-queue snapshot (the
    verdict carries its measured prefix_cache_hit_rate)."""
    checks: List[SloCheck] = []

    def check(name: str, ok: bool, observed: str, bound: str) -> None:
        checks.append(SloCheck(name=name, ok=ok, observed=observed,
                               bound=bound))
        if not ok and metrics is not None:
            metrics.inc(metric.SIM_SLO_VIOLATIONS)

    losses = ledger_report["losses"]
    check("zero_acked_write_loss", not losses,
          f"{len(losses)} lost of {ledger_report['acked_writes']} acked"
          + (f": {losses[:3]}" if losses else ""), "0 lost")
    ryw = ledger_report["ryw_violations"]
    check("read_your_writes", not ryw,
          f"{len(ryw)} violations" + (f": {ryw[:3]}" if ryw else ""), "0")

    ask = sim_metrics.get("latency", {}).get("sim_ask_latency", {})
    client_p95 = ask.get("p95_s")
    check(
        "answer_p95_client", client_p95 is None
        or client_p95 <= cfg.slo_answer_p95_s,
        f"{client_p95 if client_p95 is not None else 'n/a'} s "
        f"({ask.get('count', 0)} asks)",
        f"<= {cfg.slo_answer_p95_s} s",
    )
    worst = 0.0
    for snap in node_metrics.values():
        hist = snap.get("latency", {}).get("llm_ttft", {})
        worst = max(worst, float(hist.get("p95_s", 0.0)))
    check("answer_p95_nodes", worst <= cfg.slo_answer_p95_s,
          f"worst node llm_ttft p95 {worst:.3f} s",
          f"<= {cfg.slo_answer_p95_s} s")

    degraded = sum(_counter(s, "tutoring_degraded")
                   for s in node_metrics.values())
    requests = sum(_counter(s, "llm_requests") for s in node_metrics.values())
    rate = degraded / requests if requests else 0.0
    check("degraded_rate", rate <= cfg.slo_degraded_rate_max,
          f"{degraded}/{requests} = {rate:.3f}",
          f"<= {cfg.slo_degraded_rate_max}")

    open_breakers = {
        nid: h.get("tutoring_breaker", {}).get("state")
        for nid, h in node_health.items()
        if h.get("tutoring_breaker", {}).get("state") != "closed"
    }
    check("breakers_closed", not open_breakers,
          f"open: {open_breakers}" if open_breakers else "all closed",
          "closed on every node")

    stuck = sorted(
        set(
            [nid for nid, h in node_health.items()
             if h.get("storage_recovering")]
            + [nid for nid, s in node_metrics.items()
               if _gauge(s, "storage_recovering") > 0]
        )
    )
    check("no_stuck_storage_recovery", not stuck,
          f"recovering: {stuck}" if stuck else "none recovering", "none")

    stalls = sum(_counter(s, "raft_tick_stalls")
                 for s in node_metrics.values())
    check("tick_stalls", stalls <= cfg.slo_tick_stalls_max,
          f"{stalls} stalls summed", f"<= {cfg.slo_tick_stalls_max}")

    failed = [f"{o['kind']}: {o['detail']}" for o in event_failures]
    check("events_completed", not failed,
          f"{len(failed)} failed" + (f": {failed[:3]}" if failed else ""),
          "every planned event ok")

    hit_rate = (tutoring_metrics or {}).get("gauges", {}).get(
        "prefix_cache_hit_rate"
    )
    return SloReport(checks=checks, stage_p95s=stage_breakdown(traces),
                     prefix_cache_hit_rate=hit_rate)
