"""Frozen wire contract (`lms.proto`) plus generated messages and RPC glue.

Regenerate messages with::

    cd distributed_lms_raft_llm_tpu/proto && protoc --python_out=. lms.proto

`rpc.py` provides the stub/servicer layer (no grpcio-tools in this image).
The same adder functions work for both `grpc.server` and `grpc.aio.server`
(coroutine handlers are dispatched natively by grpc.aio).
"""


# Generated gencode does a bare `import`-style module registration under the
# name "lms_pb2"; importing it as a package submodule is fine because it has
# no cross-proto imports.
from . import lms_pb2  # noqa: F401
from .rpc import *  # noqa: F401,F403
from . import rpc  # noqa: F401
