"""gRPC client stubs and servicer bases for the LMS wire contract.

The environment has no ``grpcio-tools``/``protoc-gen-grpc`` plugin, so instead
of vendoring a thousand lines of generated boilerplate (reference:
GUI_RAFT_LLM_SourceCode/lms_pb2_grpc.py) we build the stub and servicer
classes programmatically from a declarative service table. The wire behavior
is identical to protoc-generated code: method paths are
``/<package>.<Service>/<Method>`` and payloads are the ``lms_pb2`` messages.

Usage mirrors generated code::

    stub = LMSStub(channel)
    resp = stub.Login(lms_pb2.LoginRequest(username=u, password=p))

    class MyLMS(LMSServicer): ...
    add_LMSServicer_to_server(MyLMS(), server)
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import grpc
from google.protobuf import symbol_database

from . import lms_pb2

_PACKAGE = "lms"


def _load_services() -> Dict[str, Dict[str, Tuple[Any, Any, str]]]:
    """Derive {service: {method: (req_cls, resp_cls, arity)}} from the
    generated descriptor so stubs/servicers can never drift from lms.proto.

    arity: "uu" = unary-unary, "su" = stream-unary, "us" = unary-stream
    (server streaming, e.g. StreamLLMAnswer). Bidirectional streaming is not
    part of the contract and asserts below.
    """
    sym_db = symbol_database.Default()
    services: Dict[str, Dict[str, Tuple[Any, Any, str]]] = {}
    for service_name, service in lms_pb2.DESCRIPTOR.services_by_name.items():
        methods = {}
        for method in service.methods:
            req = sym_db.GetSymbol(method.input_type.full_name)
            resp = sym_db.GetSymbol(method.output_type.full_name)
            assert not (method.client_streaming and method.server_streaming), method.full_name
            if method.server_streaming:
                arity = "us"
            elif method.client_streaming:
                arity = "su"
            else:
                arity = "uu"
            methods[method.name] = (req, resp, arity)
        services[service_name] = methods
    return services


_SERVICES = _load_services()


def _make_stub_class(service: str, methods: Dict[str, Tuple[Any, Any, str]]):
    def __init__(self, channel: grpc.Channel):
        for name, (req, resp, arity) in methods.items():
            path = f"/{_PACKAGE}.{service}/{name}"
            if arity == "uu":
                handle = channel.unary_unary(
                    path,
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                )
            elif arity == "us":  # server streaming
                handle = channel.unary_stream(
                    path,
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                )
            else:  # stream-unary
                handle = channel.stream_unary(
                    path,
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                )
            setattr(self, name, handle)

    return type(f"{service}Stub", (object,), {"__init__": __init__, "__doc__": f"Client stub for lms.{service}."})


def _unimplemented(name: str):
    def method(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details(f"Method {name} not implemented")
        raise NotImplementedError(name)

    method.__name__ = name
    return method


def _make_servicer_class(service: str, methods: Dict[str, Tuple[Any, Any, str]]):
    ns = {name: _unimplemented(name) for name in methods}
    ns["__doc__"] = f"Servicer base for lms.{service}; override the RPC methods."
    return type(f"{service}Servicer", (object,), ns)


def _make_adder(service: str, methods: Dict[str, Tuple[Any, Any, str]]):
    def adder(servicer, server: grpc.Server) -> None:
        handlers = {}
        for name, (req, resp, arity) in methods.items():
            if arity == "uu":
                factory = grpc.unary_unary_rpc_method_handler
            elif arity == "us":
                factory = grpc.unary_stream_rpc_method_handler
            else:
                factory = grpc.stream_unary_rpc_method_handler
            handlers[name] = factory(
                getattr(servicer, name),
                request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString,
            )
        generic = grpc.method_handlers_generic_handler(f"{_PACKAGE}.{service}", handlers)
        server.add_generic_rpc_handlers((generic,))

    adder.__name__ = f"add_{service}Servicer_to_server"
    return adder


_g = globals()
for _service, _methods in _SERVICES.items():
    _g[f"{_service}Stub"] = _make_stub_class(_service, _methods)
    _g[f"{_service}Servicer"] = _make_servicer_class(_service, _methods)
    _g[f"add_{_service}Servicer_to_server"] = _make_adder(_service, _methods)

__all__ = sorted(
    [f"{s}Stub" for s in _SERVICES]
    + [f"{s}Servicer" for s in _SERVICES]
    + [f"add_{s}Servicer_to_server" for s in _SERVICES]
)
