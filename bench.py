"""Headline benchmark: GPT-2 tutoring decode throughput, TPU vs reference.

Measures the BASELINE.json north-star metric — GPT-2 (124M) tutoring
tokens/sec/chip with batched concurrent student queries (batch=8,
`max_new_tokens=128`, the reference's sampling params) — on the real TPU
through the same engine the tutoring server uses. The baseline is the
reference's serving path: HF torch-CPU `GPT2LMHeadModel.generate`, one
sequential query at a time (reference: GUI_RAFT_LLM_SourceCode/
tutoring_server.py:21-29, ThreadPoolExecutor with sequential generate).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
     "ttft_p50_ms": ..., "baseline_tokens_per_sec": ...}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from functools import lru_cache, partial

import numpy as np

BATCH = 8
PROMPT_LEN = 48
MAX_NEW = 128
ROUNDS = 10

REPO = os.path.dirname(os.path.abspath(__file__))
LOCAL_CKPT_DIR = os.path.join(REPO, "data", "gpt2-local")


def ensure_local_artifacts() -> dict:
    """Checkpoint + vocab for the real-weights path (built locally: the
    image has no network and no HF cache — see scripts/make_local_checkpoint
    for why this is the strongest obtainable artifact)."""
    ckpt = os.path.join(LOCAL_CKPT_DIR, "model.safetensors")
    vocab = os.path.join(LOCAL_CKPT_DIR, "vocab.json")
    merges = os.path.join(LOCAL_CKPT_DIR, "merges.txt")
    if not all(os.path.exists(p) for p in (ckpt, vocab, merges)):
        subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "make_local_checkpoint.py")],
            check=True, timeout=900, cwd=REPO,
        )
    return {"checkpoint": ckpt, "vocab_path": vocab, "merges_path": merges}

# Fallback when torch isn't importable at bench time: torch-CPU GPT-2-small
# single-stream generate measured on this image (tokens/sec).
TORCH_CPU_FALLBACK_TPS = 15.0


def bench_tpu(model: str = "gpt2", tp: int = 1, quant: bool = False,
              batch: int = BATCH, spec_tokens: int = 0,
              greedy: bool = False) -> dict:
    import jax

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig,
        SamplingParams,
        TutoringEngine,
    )

    n_chips = max(1, len(jax.devices()))
    # The local checkpoint is gpt2-small; other sizes bench random-init
    # (BASELINE configs 2-3: gpt2-medium single chip, gpt2-large tp-sharded
    # — pass --tp when more than one chip is attached).
    artifacts = ensure_local_artifacts() if model == "gpt2" else {}
    sampling = (
        SamplingParams.greedy(max_new_tokens=MAX_NEW) if greedy
        else SamplingParams.reference_defaults(max_new_tokens=MAX_NEW)
    )
    engine = TutoringEngine(
        EngineConfig(
            model=model,
            sampling=sampling,
            length_buckets=(PROMPT_LEN, 64, 128),
            batch_buckets=tuple(sorted({1, 2, 4, 8, batch})),
            tp=tp,
            # The production serving config (tutoring_server --quant int8
            # --kv-quant): weight-only int8 + int8 KV cache, near-lossless
            # (bounds in tests/test_quant.py). quant=False measures the
            # full-precision bf16 path for continuity with earlier rounds.
            quant="int8" if quant else None,
            kv_quant=quant,
            spec_tokens=spec_tokens,
            **artifacts,
        )
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, engine.tokenizer.vocab_size,
                       (batch, PROMPT_LEN)).astype(np.int32)
    mask = np.ones((batch, PROMPT_LEN), bool)

    compile_t0 = time.monotonic()
    engine.generate_ids(ids, mask)  # compile + warm
    compile_s = time.monotonic() - compile_t0

    # Throughput under sustained load: dispatch rounds back-to-back (as a
    # loaded server pipelines batches) and sync once at the end, so the
    # host↔device round-trip latency overlaps compute instead of
    # serializing every batch.
    t0 = time.monotonic()
    results = [
        engine.generate_ids(ids, mask, measure_ttft=False, device_result=True)
        for _ in range(ROUNDS)
    ]
    results = jax.device_get(results)
    elapsed = time.monotonic() - t0
    total_tokens = sum(int(np.sum(r.lengths)) for r in results)
    tps = total_tokens / elapsed

    # TTFT, measured: the engine blocks on the first sampled token between
    # its prefill and decode programs and records the wall-clock in
    # last_ttft_s (transfer + prefill + first sample + readback).
    one_ids, one_mask = ids[:1], mask[:1]
    engine.generate_ids(one_ids, one_mask)  # compile batch-1 program
    lat = []
    for _ in range(7):
        engine.generate_ids(one_ids, one_mask)
        lat.append(engine.last_ttft_s)
    ttft_ms = sorted(lat)[len(lat) // 2] * 1000.0

    return {
        "tokens_per_sec_per_chip": tps / n_chips,
        "ttft_p50_ms": ttft_ms,
        "compile_s": compile_s,
        "batch": batch,
        "platform": jax.devices()[0].platform,
    }


def bench_paged(model: str = "gpt2", tp: int = 1, ep: int = 1,
                quant: bool = False,
                batch: int = BATCH, spec_tokens: int = 0,
                greedy: bool = False, chunk: int = 16, megastep: int = 1,
                megastep_max: int = 0, inflight: int = 2,
                max_new: int = MAX_NEW, rounds: int = ROUNDS,
                prompt_len: int = PROMPT_LEN,
                length_buckets=None, prefix_cache_blocks: int = 0,
                prefill_chunk_tokens: int = 0,
                draft_source: str = "prompt_lookup") -> dict:
    """Continuous-batching throughput/TTFT through PagedEngine directly.

    Same shape of numbers as bench_tpu so paged and paged+spec enter the
    recorded perf trajectory: sustained tokens/sec/chip with `batch` busy
    slots (rounds x batch requests churning through), then idle-engine
    batch-1 TTFT medians. Spec acceptance rides along when spec_tokens>0;
    megastep knobs and the measured host-dispatches-per-token ratio ride
    along always (the device-resident megastep's target number). The
    workload knobs (max_new/rounds/prompt_len/length_buckets) default to
    the recorded configuration; the tier-1 CPU smoke test shrinks them so
    the record path cannot rot between chip attachments.
    """
    import jax

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig,
        PagedEngine,
        SamplingParams,
    )
    from distributed_lms_raft_llm_tpu.engine.program_inventory import (
        effective_megastep_max,
    )

    n_chips = max(1, len(jax.devices()))
    artifacts = ensure_local_artifacts() if model == "gpt2" else {}
    sampling = (
        SamplingParams.greedy(max_new_tokens=max_new) if greedy
        else SamplingParams.reference_defaults(max_new_tokens=max_new)
    )
    engine = PagedEngine(
        EngineConfig(
            model=model,
            sampling=sampling,
            length_buckets=tuple(length_buckets or (prompt_len, 64, 128)),
            batch_buckets=tuple(sorted({1, 2, 4, 8, batch})),
            tp=tp,
            ep=ep,
            quant="int8" if quant else None,
            kv_quant=quant,
            spec_tokens=spec_tokens,
            draft_source=draft_source,
            **artifacts,
        ),
        slots=batch,
        chunk=chunk,
        inflight=inflight,
        megastep=megastep,
        megastep_max=megastep_max,
        prefix_cache=prefix_cache_blocks > 0,
        prefix_cache_blocks=max(1, prefix_cache_blocks),
        prefill_chunk_tokens=prefill_chunk_tokens,
    )
    rng = np.random.default_rng(0)
    prompts = [
        engine.tokenizer.decode(
            rng.integers(0, engine.tokenizer.vocab_size, prompt_len).tolist()
        )
        for _ in range(rounds * batch)
    ]
    compile_s = engine.warmup()

    engine.pop_spec_stats()
    engine.pop_dispatch_stats()
    engine.total_generated_tokens = 0
    t0 = time.monotonic()
    for p in prompts:
        engine.submit(p)
    engine.drain()
    elapsed = time.monotonic() - t0
    tps = engine.total_generated_tokens / elapsed
    spec_stats = engine.pop_spec_stats()
    (dispatches, emitted, dead_lanes, stall_ms,
     stalled_tokens) = engine.pop_dispatch_stats()
    engine.pop_ttfts()

    # Idle-engine TTFT (same protocol as bench_tpu: median of 7 batch-1
    # runs, measured submit -> first token on host).
    lat = []
    for _ in range(7):
        rid = engine.submit(prompts[0])
        engine.drain()
        lat.append(engine.pop_ttfts()[rid])
    ttft_ms = sorted(lat)[len(lat) // 2] * 1000.0

    out = {
        "tokens_per_sec_per_chip": tps / n_chips,
        "requests_per_s": len(prompts) / elapsed,
        "ttft_p50_ms": ttft_ms,
        "compile_s": compile_s,
        # Mesh block (BENCH schema): axis sizes the engine actually built,
        # the per-chip vs total KV residency the tp sharding buys, and
        # both tok/s views — total for capacity planning, per-chip for
        # efficiency comparisons across mesh sizes.
        "mesh": {
            "tp": engine.tp,
            "ep": engine.ep,
            "dp": int(engine.mesh.shape.get("dp", 1)),
            "devices": n_chips,
            "kv_bytes_total": engine.kv_bytes_total,
            "kv_bytes_per_chip": engine.kv_bytes_per_chip,
            "tokens_per_sec_total": tps,
            "tokens_per_sec_per_chip": tps / n_chips,
        },
        "batch": batch,
        "chunk": chunk,
        "megastep": megastep,
        "megastep_max": effective_megastep_max(megastep, megastep_max),
        "inflight": inflight,
        "host_dispatches_per_token": (
            dispatches / emitted if emitted else None
        ),
        "megastep_dead_lane_tokens": dead_lanes,
        # Stall-free admission before/after: decode-train pause charged
        # to sequential admission (0 by construction when
        # prefill_chunk_tokens > 0 stages admissions into the scan).
        "prefill_chunk_tokens": prefill_chunk_tokens,
        "prefill_stall_ms": round(stall_ms, 2),
        "decode_stalled_tokens": stalled_tokens,
        "platform": jax.devices()[0].platform,
    }
    if spec_stats is not None:
        windows, spec_emitted = spec_stats
        out["spec_tokens_per_window"] = (
            spec_emitted / windows if windows else None
        )
    prefix_stats = engine.pop_prefix_stats()
    if prefix_stats is not None:
        hit, total, _evicted, _blocks = prefix_stats
        out["prefix_cache_blocks"] = prefix_cache_blocks
        out["prefix_cache_hit_rate"] = hit / total if total else None
    return out


def bench_shared_prefix(model: str = "gpt2", tp: int = 1,
                        quant: bool = False, n_requests: int = 16,
                        prefix_len: int = 96, suffix_len: int = 16,
                        max_new: int = 32, chunk: int = 16,
                        slots: int = BATCH, greedy: bool = True,
                        prefix_cache_blocks: int = 512,
                        prefix_block_tokens: int = 16,
                        length_buckets=None) -> dict:
    """The shared-prefix scenario: N requests against one common M-token
    course context, cold vs warm.

    Phase A (cold) submits `n_requests` prompts with pairwise-DISTINCT
    prefixes — every admission is a full prefill. Phase B (warm) submits
    `n_requests` prompts sharing ONE common prefix: the first seeds the
    radix tree, the rest splice its blocks and partial-prefill only
    their `suffix_len`-token tails. The record carries mean prefill
    dispatch ms and tokens/s for each phase plus the measured hit rate —
    the ISSUE acceptance number is warm prefill device time per request
    dropping >= 2x at steady-state hit rate on a same-course workload.
    """
    import jax

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig,
        PagedEngine,
        SamplingParams,
    )

    n_chips = max(1, len(jax.devices()))
    artifacts = ensure_local_artifacts() if model == "gpt2" else {}
    total_len = prefix_len + suffix_len
    sampling = (
        SamplingParams.greedy(max_new_tokens=max_new) if greedy
        else SamplingParams.reference_defaults(max_new_tokens=max_new)
    )
    engine = PagedEngine(
        EngineConfig(
            model=model,
            sampling=sampling,
            length_buckets=tuple(
                length_buckets or sorted({suffix_len * 2, total_len})
            ),
            batch_buckets=(1, 2, 4, 8),
            tp=tp,
            quant="int8" if quant else None,
            kv_quant=quant,
            **artifacts,
        ),
        slots=slots,
        chunk=chunk,
        prefix_cache=True,
        prefix_cache_blocks=prefix_cache_blocks,
        prefix_block_tokens=prefix_block_tokens,
    )
    filler = ("the raft consensus algorithm elects a leader, replicates "
              "a log, and commits entries across the course cluster. ")

    @lru_cache(maxsize=None)
    def context_text(seed: int) -> str:
        # A natural-text course context measuring ~prefix_len tokens
        # (identical text => identical token prefix across requests —
        # what the radix tree keys on). Cached per seed: the warm phase
        # reuses one context and the host tokenizer work must not leak
        # into a benchmark of engine prefill time.
        text = f"course {seed} assignment context: " + filler
        while len(engine.tokenizer.encode(text)) < prefix_len:
            text += filler
        return engine.tokenizer.decode(
            engine.tokenizer.encode(text)[:prefix_len]
        )

    def make_prompt(prefix_seed: int, i: int) -> str:
        return context_text(prefix_seed) + f" student question {i}: why?"

    compile_s = engine.warmup()

    def run_phase(prompts):
        engine.pop_prefix_stats()
        engine.pop_program_times()
        engine.total_generated_tokens = 0
        t0 = time.monotonic()
        for p in prompts:
            engine.submit(p)
        engine.drain()
        elapsed = time.monotonic() - t0
        prefill_ms = {}
        for name, _start, wall_s in engine.pop_program_times():
            if name in ("prefill", "partial_prefill", "load_block"):
                prefill_ms.setdefault(name, []).append(wall_s * 1000.0)
        hit, total, _ev, _blocks = engine.pop_prefix_stats()
        return dict(
            tokens_per_sec_per_chip=(
                engine.total_generated_tokens / elapsed / n_chips
            ),
            prefill_dispatches={
                k: len(v) for k, v in prefill_ms.items()
            },
            prefill_ms_mean={
                k: sum(v) / len(v) for k, v in prefill_ms.items()
            },
            hit_rate=hit / total if total else 0.0,
        )

    cold = run_phase([make_prompt(1000 + i, i) for i in range(n_requests)])
    engine.prefix_cache.clear()
    warm = run_phase([make_prompt(7, i) for i in range(n_requests)])

    cold_ms = cold["prefill_ms_mean"].get("prefill")
    warm_ms = warm["prefill_ms_mean"].get("partial_prefill")
    return {
        "metric": "paged_shared_prefix_prefill_speedup",
        "value": round(cold_ms / warm_ms, 2) if cold_ms and warm_ms
        else None,
        "unit": "x cold/warm prefill dispatch ms",
        "n_requests": n_requests,
        "prefix_tokens": prefix_len,
        "suffix_tokens": suffix_len,
        "prefix_cache_blocks": prefix_cache_blocks,
        "prefill_ms_cold": round(cold_ms, 3) if cold_ms else None,
        "prefill_ms_warm": round(warm_ms, 3) if warm_ms else None,
        "tokens_per_sec_per_chip_cold": round(
            cold["tokens_per_sec_per_chip"], 2
        ),
        "tokens_per_sec_per_chip_warm": round(
            warm["tokens_per_sec_per_chip"], 2
        ),
        "prefix_cache_hit_rate": round(warm["hit_rate"], 3),
        "cold_hit_rate": round(cold["hit_rate"], 3),
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
    }


def bench_sweep(model: str = "gpt2", tp: int = 1, quant: bool = False,
                slots_grid=(16, 32, 64), inflight_grid=(2, 3, 4),
                megastep_grid=(1, 4, 8), spec_tokens: int = 0,
                greedy: bool = False, chunk: int = 16,
                max_new: int = MAX_NEW, rounds: int = 2,
                prompt_len: int = PROMPT_LEN, length_buckets=None,
                prefix_cache_blocks: int = 0,
                prefill_chunk_tokens: int = 0,
                draft_source: str = "prompt_lookup") -> list:
    """Round-6 grid: slots x inflight-depth x megastep rungs, one
    BENCH-schema record per point.

    Each point is an independent `bench_paged` run (fresh engine, same
    seeded workload scaled to the slot count), so a sweep answers the
    ROADMAP's open questions — slot counts beyond 16, inflight-depth,
    and megastep ladders — in one command whose output is `jq`-able
    straight into BENCH_NOTES. `rounds` defaults low (2) because a sweep
    multiplies runs; raise it for tighter chip numbers. CPU-smoked in
    tests/test_bench_record.py so the grid path cannot rot between chip
    attachments."""
    records = []
    for slots in slots_grid:
        for inflight in inflight_grid:
            for mega in megastep_grid:
                out = bench_paged(
                    model=model, tp=tp, quant=quant, batch=slots,
                    spec_tokens=spec_tokens, greedy=greedy, chunk=chunk,
                    megastep=mega, megastep_max=mega, inflight=inflight,
                    max_new=max_new, rounds=rounds,
                    prompt_len=prompt_len, length_buckets=length_buckets,
                    prefix_cache_blocks=prefix_cache_blocks,
                    prefill_chunk_tokens=prefill_chunk_tokens,
                    draft_source=draft_source,
                )
                records.append({
                    "metric": (
                        f"paged_sweep_slots{slots}_inflight{inflight}"
                        f"_mega{mega}"
                    ),
                    "value": round(out["tokens_per_sec_per_chip"], 2),
                    "unit": "tokens/sec/chip",
                    "slots": slots,
                    **{k: out[k] for k in (
                        "requests_per_s", "ttft_p50_ms", "chunk",
                        "megastep", "megastep_max", "inflight",
                        "host_dispatches_per_token",
                        "megastep_dead_lane_tokens",
                        "prefill_chunk_tokens", "prefill_stall_ms",
                        "decode_stalled_tokens", "platform",
                    )},
                })
    return records


def bench_score_scenario(model: str = "gpt2", tp: int = 1,
                         quant: bool = False, slots: int = BATCH,
                         chunk: int = 16, megastep: int = 1,
                         megastep_max: int = 0, inflight: int = 2,
                         interactive: int = 24, arrival_s: float = 0.03,
                         score_texts_n: int = 128,
                         score_text_tokens: int = 48,
                         max_new: int = MAX_NEW,
                         prompt_len: int = PROMPT_LEN,
                         length_buckets=None, greedy: bool = False) -> dict:
    """The two-tenant scenario: interactive load with the background
    scoring tenant OFF then ON, through the real PagedQueue co-scheduler.

    Phase OFF drives `interactive` requests at `arrival_s` spacing and
    records interactive tokens/s + TTFT p90. Phase ON replays the same
    arrivals with a `score_texts_n`-text bulk job submitted up front:
    quanta harvest the idle lanes (arrival gaps + the post-workload
    drain). The acceptance claims the record must witness: total
    tokens/s/chip RISES with the tenant on (the harvest), interactive
    p90 TTFT HOLDS (quanta admit only while nothing interactive is
    pending — `quanta_with_pending` stays 0 and every preemption wait is
    bounded by one quantum), and the warmed score domain means ZERO live
    compiles (EngineConfig.scoring warms it; the engine is reused across
    both phases so phase ON compiles nothing).
    """
    import asyncio

    import jax

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig,
        PagedEngine,
        PagedQueue,
        SamplingParams,
        ScoringManager,
    )
    from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

    n_chips = max(1, len(jax.devices()))
    artifacts = ensure_local_artifacts() if model == "gpt2" else {}
    sampling = (
        SamplingParams.greedy(max_new_tokens=max_new) if greedy
        else SamplingParams.reference_defaults(max_new_tokens=max_new)
    )
    engine = PagedEngine(
        EngineConfig(
            model=model,
            sampling=sampling,
            length_buckets=tuple(length_buckets or (prompt_len, 64, 128)),
            batch_buckets=tuple(sorted({1, 2, 4, 8, min(8, slots)})),
            tp=tp,
            quant="int8" if quant else None,
            kv_quant=quant,
            scoring=True,
            **artifacts,
        ),
        slots=slots, chunk=chunk, inflight=inflight,
        megastep=megastep, megastep_max=megastep_max,
    )
    compile_s = engine.warmup()
    rng = np.random.default_rng(0)
    prompts = [
        engine.tokenizer.decode(
            rng.integers(0, engine.tokenizer.vocab_size, prompt_len).tolist()
        )
        for _ in range(interactive)
    ]
    corpus = [
        engine.tokenizer.decode(
            rng.integers(0, engine.tokenizer.vocab_size,
                         score_text_tokens).tolist()
        )
        for _ in range(score_texts_n)
    ]

    async def phase(with_scoring: bool) -> dict:
        metrics = Metrics()
        scorer = (ScoringManager(engine, metrics=metrics,
                                 max_job_texts=len(corpus))
                  if with_scoring else None)
        queue = PagedQueue(engine, metrics=metrics, scorer=scorer)
        await queue.start()
        engine.total_generated_tokens = 0
        t0 = time.monotonic()
        if scorer is not None:
            scorer.submit(corpus, purpose="calibration")
        tasks = []
        for p in prompts:
            tasks.append(asyncio.ensure_future(queue.submit(p)))
            await asyncio.sleep(arrival_s)
        await asyncio.gather(*tasks)
        interactive_s = time.monotonic() - t0
        interactive_tokens = engine.total_generated_tokens
        if scorer is not None:
            # Drain the bulk backlog: pure idle-lane time from here on.
            while not scorer.done():
                await asyncio.sleep(0.01)
        elapsed = time.monotonic() - t0
        p90 = metrics.hist("ttft").percentile(90) or 0.0
        snap = metrics.snapshot()
        stats = scorer.stats() if scorer is not None else {}
        out = dict(
            interactive_s=interactive_s,
            elapsed_s=elapsed,
            interactive_tokens=interactive_tokens,
            scored_tokens=stats.get("scored_tokens", 0),
            ttft_p90_ms=p90 * 1000.0,
            quanta=stats.get("quanta", 0),
            jobs_completed=stats.get("jobs_completed", 0),
            quanta_with_pending=stats.get("quanta_with_pending", 0),
            max_quantum_wall_ms=stats.get("max_quantum_wall_ms", 0.0),
            preempt_wait_ms=snap.get("counters", {}).get(
                "score_preempt_wait_ms", 0
            ),
            max_preempt_wait_ms=queue.max_preempt_wait_s * 1000.0,
        )
        await queue.close()
        return out

    off = asyncio.run(phase(False))
    on = asyncio.run(phase(True))
    total_off = off["interactive_tokens"] / off["elapsed_s"] / n_chips
    total_on = (
        (on["interactive_tokens"] + on["scored_tokens"])
        / on["elapsed_s"] / n_chips
    )
    return {
        "metric": "paged_score_tenant_total_tokens_per_sec_per_chip",
        "value": round(total_on, 2),
        "unit": "tokens/sec/chip",
        "interactive_requests": interactive,
        "arrival_s": arrival_s,
        "score_texts": score_texts_n,
        "interactive_tokens_per_sec_per_chip_off": round(
            off["interactive_tokens"] / off["elapsed_s"] / n_chips, 2
        ),
        "interactive_tokens_per_sec_per_chip_on": round(
            on["interactive_tokens"] / on["interactive_s"] / n_chips, 2
        ),
        "total_tokens_per_sec_per_chip_off": round(total_off, 2),
        "total_tokens_per_sec_per_chip_on": round(total_on, 2),
        "ttft_p90_ms_off": round(off["ttft_p90_ms"], 2),
        "ttft_p90_ms_on": round(on["ttft_p90_ms"], 2),
        "ttft_p90_delta_ms": round(
            on["ttft_p90_ms"] - off["ttft_p90_ms"], 2
        ),
        "scoring_quanta": on["quanta"],
        "scoring_jobs_completed": on["jobs_completed"],
        "scored_tokens": on["scored_tokens"],
        # The admission-policy witnesses: quanta admitted while anything
        # interactive waited (must be 0), and the worst single wait an
        # interactive arrival paid for an in-flight quantum (bounded by
        # one quantum wall).
        "quanta_with_pending": on["quanta_with_pending"],
        "max_quantum_wall_ms": on["max_quantum_wall_ms"],
        "score_preempt_wait_ms": on["preempt_wait_ms"],
        "max_preempt_wait_ms": round(on["max_preempt_wait_ms"], 2),
        "slots": slots,
        "chunk": chunk,
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
    }


def bench_torch_baseline(model: str = "gpt2", budget_new_tokens: int = 32) -> float:
    """Reference path: torch-CPU GPT-2 (matching size), sequential queries."""
    arch = {
        "gpt2": dict(),
        # The reference has no MoE; its comparable is the same dense trunk
        # (gpt2-moe activates ~gpt2-small FLOPs per token).
        "gpt2-moe": dict(),
        "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
        "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
    }[model]
    try:
        import torch
        import transformers

        cfg = transformers.GPT2Config(**arch)
        torch.manual_seed(0)
        model = transformers.GPT2LMHeadModel(cfg)
        model.eval()
        ids = torch.randint(0, 50000, (1, PROMPT_LEN))
        with torch.no_grad():
            model.generate(  # warm
                ids, max_new_tokens=4, do_sample=True, top_k=50, top_p=0.9,
                temperature=0.7, repetition_penalty=1.2,
                pad_token_id=cfg.eos_token_id,
            )
            t0 = time.monotonic()
            out = model.generate(
                ids, max_new_tokens=budget_new_tokens, do_sample=True,
                top_k=50, top_p=0.9, temperature=0.7, repetition_penalty=1.2,
                pad_token_id=cfg.eos_token_id,
            )
            elapsed = time.monotonic() - t0
        produced = out.shape[1] - PROMPT_LEN
        return produced / elapsed
    except Exception as e:  # torch missing/broken: use the recorded number
        print(f"# torch baseline unavailable ({e}); using fallback",
              file=sys.stderr)
        return TORCH_CPU_FALLBACK_TPS


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2",
                    choices=["gpt2", "gpt2-medium", "gpt2-large",
                             "gpt2-moe"],
                    help="BASELINE config to bench (default: the headline; "
                         "gpt2-moe = 8-expert top-2 small trunk, random "
                         "init)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel ways (config 4: gpt2-large tp); "
                         "with --paged the slot KV cache and prefix-cache "
                         "blocks shard their heads axis over tp too, and "
                         "the record's mesh block carries per-chip KV "
                         "bytes")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel ways (MoE models only; shards "
                         "the expert stacks — paged: requires gpt2-moe)")
    ap.add_argument("--batch", type=int, default=BATCH,
                    help="device batch (BASELINE config is 8)")
    ap.add_argument("--spec-tokens", type=int, default=0,
                    help="speculative decoding draft window (engine/draft.py "
                         "kernels; exact). Measured win is on the greedy "
                         "low-batch path — pair with --greedy --batch 1, or "
                         "with --paged for the unified serving config")
    ap.add_argument("--greedy", action="store_true",
                    help="temperature-0 sampling instead of the reference "
                         "params (the speculative serving configuration)")
    ap.add_argument("--paged", action="store_true",
                    help="bench the continuous-batching PagedEngine instead "
                         "of the group-batched engine (composes with "
                         "--spec-tokens: per-slot verify windows)")
    ap.add_argument("--chunk", type=int, default=16,
                    help="paged: tokens (spec: verify windows) per device "
                         "chunk (one step program; a megastep fuses K)")
    ap.add_argument("--megastep", type=int, default=1,
                    help="paged: starting K of the megastep controller — "
                         "chunks fused per host dispatch (1 = chunk loop)")
    ap.add_argument("--megastep-max", type=int, default=0,
                    help="paged: megastep controller ceiling (0 = follow "
                         "--megastep)")
    ap.add_argument("--inflight", type=int, default=2,
                    help="paged: dispatch pipelining depth")
    ap.add_argument("--prefix-cache-blocks", type=int, default=0,
                    help="paged: enable the radix shared-prefix KV cache "
                         "with this block budget (0 = off); the record "
                         "carries the measured hit rate")
    ap.add_argument("--prefill-chunk-tokens", type=int, default=0,
                    help="paged: fused stall-free admission — stage "
                         "prompts and prefill this many tokens per "
                         "megastep scan iteration inside the decode "
                         "program (0 = sequential admission; the record "
                         "carries prefill_stall_ms/decode_stalled_tokens)")
    ap.add_argument("--sweep", action="store_true",
                    help="paged: run the round-6 grid (slots x inflight "
                         "x megastep rungs) and print one BENCH-schema "
                         "JSON line per point instead of the single "
                         "headline record")
    ap.add_argument("--sweep-slots", default="16,32,64",
                    help="comma-separated slot counts for --sweep")
    ap.add_argument("--sweep-inflight", default="2,3,4",
                    help="comma-separated inflight depths for --sweep")
    ap.add_argument("--sweep-megasteps", default="1,4,8",
                    help="comma-separated megastep rungs for --sweep")
    ap.add_argument("--sweep-rounds", type=int, default=2,
                    help="request rounds per sweep grid point (2 keeps a "
                         "full grid cheap; raise for tighter chip numbers)")
    ap.add_argument("--draft-source", default="prompt_lookup",
                    choices=["prompt_lookup", "ngram"],
                    help="paged+spec draft source: prompt_lookup = "
                         "most-recent n-gram continuation; ngram = per-slot "
                         "modal-continuation table (higher acceptance at "
                         "temperature>0)")
    ap.add_argument("--score-scenario", action="store_true",
                    help="paged: run the two-tenant scenario (interactive "
                         "load with the background scoring tenant off "
                         "then on) and print its BENCH record — total "
                         "tok/s/chip must rise, interactive p90 TTFT "
                         "must hold, quanta_with_pending must be 0")
    ap.add_argument("--score-texts", type=int, default=128,
                    help="bulk-job corpus size for --score-scenario")
    ap.add_argument("--score-interactive", type=int, default=24,
                    help="interactive requests per phase for "
                         "--score-scenario")
    ap.add_argument("--prefix-scenario", action="store_true",
                    help="paged: also run the shared-prefix scenario (N "
                         "requests against one common course context, "
                         "prefill ms + tokens/s cold vs warm) and embed "
                         "its record under \"shared_prefix\"")
    ap.add_argument("--config", default=None,
                    help="TOML deployment file; [tutoring] model/tp apply")
    args = ap.parse_args()
    if args.config:
        from distributed_lms_raft_llm_tpu.config import load_config

        t = load_config(args.config).tutoring
        if args.model == "gpt2" and t.model in ("gpt2", "gpt2-medium",
                                                "gpt2-large", "gpt2-moe"):
            args.model = t.model
        if args.tp == 1:
            args.tp = t.tp
        if args.ep == 1:
            args.ep = t.ep
    extra = dict(spec_tokens=args.spec_tokens, greedy=args.greedy)
    if args.score_scenario:
        record = bench_score_scenario(
            args.model, args.tp, quant=args.tp == 1, slots=args.batch,
            chunk=args.chunk, megastep=args.megastep,
            megastep_max=args.megastep_max, inflight=args.inflight,
            interactive=args.score_interactive,
            score_texts_n=args.score_texts, greedy=args.greedy,
        )
        print(json.dumps(record))
        return
    if args.sweep:
        grid = bench_sweep(
            args.model, args.tp, quant=args.tp == 1,
            slots_grid=tuple(int(s) for s in args.sweep_slots.split(",")),
            inflight_grid=tuple(
                int(s) for s in args.sweep_inflight.split(",")
            ),
            megastep_grid=tuple(
                int(s) for s in args.sweep_megasteps.split(",")
            ),
            chunk=args.chunk,
            rounds=args.sweep_rounds,
            prefix_cache_blocks=args.prefix_cache_blocks,
            prefill_chunk_tokens=args.prefill_chunk_tokens,
            draft_source=args.draft_source,
            **extra,
        )
        for record in grid:
            print(json.dumps(record))
        return
    run = bench_tpu
    if args.paged:
        run = partial(bench_paged, ep=args.ep, chunk=args.chunk,
                      megastep=args.megastep,
                      megastep_max=args.megastep_max,
                      inflight=args.inflight,
                      prefix_cache_blocks=args.prefix_cache_blocks,
                      prefill_chunk_tokens=args.prefill_chunk_tokens,
                      draft_source=args.draft_source)
    quant = (run(args.model, args.tp, quant=True, batch=args.batch, **extra)
             if args.tp == 1 else None)
    tpu = run(args.model, args.tp, batch=args.batch, **extra)
    baseline_tps = bench_torch_baseline(args.model)
    name = {"gpt2": "gpt2_small"}.get(args.model, args.model.replace("-", "_"))
    if args.tp > 1:
        name += f"_tp{args.tp}"
    if args.ep > 1:
        name += f"_ep{args.ep}"
    if args.paged:
        name += "_paged"
    if args.paged and args.megastep > 1:
        name += f"_mega{args.megastep}"
    if args.paged and args.prefill_chunk_tokens:
        name += f"_fusedadm{args.prefill_chunk_tokens}"
    if args.greedy:
        name += "_greedy"
    if args.spec_tokens:
        name += f"_spec{args.spec_tokens}"
    head = quant or tpu  # headline = the production serving config
    value = round(head["tokens_per_sec_per_chip"], 2)
    record = {
        "metric": f"{name}_tutoring_decode_tokens_per_sec_per_chip"
                  f"_batch{head['batch']}"
                  + ("_int8w_int8kv" if quant else ""),
        "value": value,
        "unit": "tokens/sec/chip",
        "vs_baseline": round(value / max(baseline_tps, 1e-9), 2),
        "ttft_p50_ms": round(head["ttft_p50_ms"], 2),
        "baseline_tokens_per_sec": round(baseline_tps, 2),
        "compile_s": round(head["compile_s"], 1),
        "platform": head["platform"],
    }
    if "requests_per_s" in head:
        record["requests_per_s"] = round(head["requests_per_s"], 2)
    if "mesh" in head:
        # Per-chip accounting for multi-chip paged serving: axis sizes,
        # the KV residency the tp sharding splits, both tok/s views.
        mesh = dict(head["mesh"])
        mesh["tokens_per_sec_total"] = round(mesh["tokens_per_sec_total"], 2)
        mesh["tokens_per_sec_per_chip"] = round(
            mesh["tokens_per_sec_per_chip"], 2
        )
        record["mesh"] = mesh
    if "megastep" in head:
        # Paged runs carry the megastep configuration and its target
        # ratio so the recorded trajectory shows host round trips per
        # token shrinking as K rises.
        record["chunk"] = head["chunk"]
        record["megastep"] = head["megastep"]
        record["megastep_max"] = head["megastep_max"]
        record["inflight"] = head["inflight"]
        if head.get("host_dispatches_per_token") is not None:
            record["host_dispatches_per_token"] = round(
                head["host_dispatches_per_token"], 4
            )
        record["megastep_dead_lane_tokens"] = (
            head["megastep_dead_lane_tokens"]
        )
        record["prefill_chunk_tokens"] = head["prefill_chunk_tokens"]
        record["prefill_stall_ms"] = head["prefill_stall_ms"]
        record["decode_stalled_tokens"] = head["decode_stalled_tokens"]
    if head.get("spec_tokens_per_window") is not None:
        record["spec_tokens_per_window"] = round(
            head["spec_tokens_per_window"], 2
        )
    if head.get("prefix_cache_hit_rate") is not None:
        record["prefix_cache_blocks"] = head["prefix_cache_blocks"]
        record["prefix_cache_hit_rate"] = round(
            head["prefix_cache_hit_rate"], 3
        )
    if args.paged and args.prefix_scenario:
        record["shared_prefix"] = bench_shared_prefix(
            args.model, args.tp, quant=args.tp == 1, chunk=args.chunk,
            prefix_cache_blocks=args.prefix_cache_blocks or 512,
        )
    if quant:
        # Full-precision numbers ride along for cross-round continuity.
        record["bf16_tokens_per_sec"] = round(
            tpu["tokens_per_sec_per_chip"], 2
        )
        record["bf16_ttft_p50_ms"] = round(tpu["ttft_p50_ms"], 2)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
